"""Sharded-execution tests on the virtual 8-device CPU mesh.

The idiomatic-JAX upgrade over the reference's mpirun example programs
(SURVEY.md S4): sharded and unsharded runs are compared numerically in one
process.  conftest.py forces JAX_PLATFORMS=cpu with
xla_force_host_platform_device_count=8.
"""

import jax
import numpy as np
import pytest

import rustpde_mpi_tpu as rp
from rustpde_mpi_tpu import Navier2D
from rustpde_mpi_tpu.parallel import make_mesh, use_mesh
from rustpde_mpi_tpu.solver import Poisson


def test_virtual_mesh_has_devices():
    assert jax.device_count() == 8


def test_sharded_transform_roundtrip_matches():
    # jit so the pencil constraints actually shard (eager placement skips
    # non-divisible dims); 33x32 exercises GSPMD padding on axis 0
    mesh = make_mesh()
    space = rp.Space2(rp.cheb_dirichlet(33), rp.cheb_dirichlet(32))
    rng = np.random.default_rng(1)
    v = rng.standard_normal(space.shape_physical)
    ref = np.asarray(space.forward(v))
    with use_mesh(mesh):
        out = np.asarray(jax.jit(space.forward)(v))
    np.testing.assert_allclose(out, ref, atol=1e-13)


def test_sharded_poisson_matches():
    mesh = make_mesh()
    space = rp.Space2(rp.cheb_dirichlet(32), rp.cheb_dirichlet(33))
    solver = Poisson(space, (1.0, 1.0))
    x, y = space.base_x.points, space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    n = np.pi / 2.0
    f = -2.0 * n * n * np.cos(n * X) * np.cos(n * Y)
    fhat = space.to_ortho(space.forward(f))
    ref = np.asarray(solver.solve(fhat))
    with use_mesh(mesh):
        out = np.asarray(jax.jit(solver.solve)(fhat))
    np.testing.assert_allclose(out, ref, atol=1e-12)


def test_sharded_navier_matches_unsharded():
    def build(mesh):
        model = Navier2D(33, 32, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False, mesh=mesh)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        return model

    serial = build(None)
    sharded = build(make_mesh())
    serial.update_n(10)
    sharded.update_n(10)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(sharded.state, attr)),
            np.asarray(getattr(serial.state, attr)),
            atol=1e-12,
            err_msg=attr,
        )
    assert sharded.eval_nu() == pytest.approx(serial.eval_nu(), abs=1e-12)
    assert sharded.eval_re() == pytest.approx(serial.eval_re(), abs=1e-10)


def test_sharded_navier_nondivisible_grid():
    # 129 not divisible by 8: GSPMD pads — results must still match
    def build(mesh):
        model = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False, mesh=mesh)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        return model

    serial = build(None)
    sharded = build(make_mesh())
    serial.update_n(5)
    sharded.update_n(5)
    np.testing.assert_allclose(
        np.asarray(sharded.state.temp), np.asarray(serial.state.temp), atol=1e-12
    )


def test_sharded_state_placement():
    # ny = 34 -> spectral axis 1 extent 32, divisible by the 8-device mesh:
    # current JAX rounds a with_sharding_constraint on a non-divisible dim to
    # REPLICATED (it used to keep an uneven sharding), so the x-pencil
    # placement convention is only *expressible* on divisible extents —
    # uneven grids still compute correctly (test_sharded_navier_nondivisible_grid)
    model = Navier2D(33, 34, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False, mesh=make_mesh())
    model.update()
    # spectral state lives in x-pencils (axis 1 sharded) per the reference
    # convention (/root/reference/src/field_mpi.rs:71-88): shards must be
    # spread over devices and split along axis 1 only
    shards = model.state.temp.addressable_shards
    assert len({s.device for s in shards}) > 1
    for s in shards:
        i0, i1 = s.index
        assert i0 == slice(None) or (i0.start in (0, None) and i0.stop in (None, 31))
        assert i1 != slice(None)  # axis 1 actually split


@pytest.mark.slow
def test_sharded_adjoint_matches_serial():
    """Steady-state adjoint descent under the pencil mesh == serial."""
    import jax
    from jax.sharding import Mesh

    from rustpde_mpi_tpu import Navier2DAdjoint
    from rustpde_mpi_tpu.parallel.mesh import AXIS

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs >=4 virtual devices")
    mesh = Mesh(np.array(devices[:4]), (AXIS,))
    serial = Navier2DAdjoint.new_confined(17, 17, 1e4, 1.0, 5e-3, 1.0, "rbc")
    sharded = Navier2DAdjoint.new_confined(17, 17, 1e4, 1.0, 5e-3, 1.0, "rbc", mesh=mesh)
    for m in (serial, sharded):
        m.set_temperature(0.5, 1.0, 1.0)
        m.set_velocity(0.5, 1.0, 1.0)
    serial.update_n(20)
    sharded.update_n(20)
    np.testing.assert_allclose(
        np.asarray(sharded.state.temp), np.asarray(serial.state.temp), atol=1e-11
    )
    assert sharded.residual() == pytest.approx(serial.residual(), rel=1e-9)


@pytest.mark.slow
def test_sharded_lnse_matches_serial():
    """Linearized NSE forward + adjoint steps under the mesh == serial."""
    import jax
    from jax.sharding import Mesh

    from rustpde_mpi_tpu import MeanFields, Navier2DLnse
    from rustpde_mpi_tpu.parallel.mesh import AXIS

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs >=4 virtual devices")
    mesh = Mesh(np.array(devices[:4]), (AXIS,))
    mean = MeanFields.new_rbc(17, 17)
    serial = Navier2DLnse.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", mean=mean)
    sharded = Navier2DLnse.new_confined(
        17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", mean=mean, mesh=mesh
    )
    serial.init_random(1e-3, seed=2)
    sharded.init_random(1e-3, seed=2)  # same host RNG -> identical ICs
    serial.update_n(10)
    sharded.update_n(10)
    np.testing.assert_allclose(
        np.asarray(sharded.state.temp), np.asarray(serial.state.temp), atol=1e-11
    )


@pytest.mark.slow
def test_sharded_navier_with_fast_transforms():
    """The four-step transform + cumsum-derivative paths must shard cleanly
    under the pencil mesh (the flagship grids sit above the auto gates, so
    dryrun_multichip exercises exactly this combination)."""
    from rustpde_mpi_tpu import bases
    from rustpde_mpi_tpu.ops import fourstep

    mode, fderiv = fourstep._MODE, bases._FAST_DERIV
    fourstep._MODE = "1"
    bases._FAST_DERIV = "1"
    try:

        def build(mesh):
            model = Navier2D(
                33, 32, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False, mesh=mesh
            )
            model.set_velocity(0.1, 1.0, 1.0)
            model.set_temperature(0.1, 1.0, 1.0)
            return model

        serial = build(None)
        sharded = build(make_mesh())
        serial.update_n(5)
        sharded.update_n(5)
        np.testing.assert_allclose(
            np.asarray(sharded.state.temp), np.asarray(serial.state.temp), atol=1e-12
        )
    finally:
        fourstep._MODE = mode
        bases._FAST_DERIV = fderiv


# -- periodic (split Re/Im Fourier) configuration under the mesh -------------
# The split spectral layout (doubled axis-0 blocks, bases.py SplitFourierBase)
# interacts non-trivially with the pencil specs; these prove it correct under
# GSPMD sharding (VERDICT r3 #4; reference behavior
# /root/reference/src/navier_stokes_mpi/navier.rs:364-487 +
# examples/navier_periodic_mpi.rs / navier_periodic_hc_mpi.rs).


def _build_periodic(mesh, nx, ny, bc):
    model = Navier2D(nx, ny, 1e4, 1.0, 5e-3, 1.0, bc, periodic=True, mesh=mesh)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    return model


@pytest.mark.parametrize("bc", ["rbc", "hc"])
def test_sharded_periodic_matches_unsharded(bc):
    serial = _build_periodic(None, 32, 17, bc)
    sharded = _build_periodic(make_mesh(), 32, 17, bc)
    serial.update_n(10)
    sharded.update_n(10)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(sharded.state, attr)),
            np.asarray(getattr(serial.state, attr)),
            atol=1e-12,
            err_msg=attr,
        )
    assert sharded.eval_nu() == pytest.approx(serial.eval_nu(), abs=1e-12)


def test_sharded_periodic_nondivisible_nx():
    # nx=20: neither the physical axis (20) nor the split spectral axis is
    # divisible by 8 devices -> GSPMD pads; results must still match,
    # including the pin of the zero mode's Im row (bases.py pin_zero_mode)
    serial = _build_periodic(None, 20, 17, "rbc")
    sharded = _build_periodic(make_mesh(), 20, 17, "rbc")
    serial.update_n(8)
    sharded.update_n(8)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(sharded.state, attr)),
            np.asarray(getattr(serial.state, attr)),
            atol=1e-12,
            err_msg=attr,
        )


@pytest.mark.slow
def test_sharded_production_shape_matches():
    """Mesh-vs-serial at a production-class shape (>=257^2, f64) where
    padding/uneven shards actually bite (VERDICT r3 #5): 257 = 8*32+1 on the
    Chebyshev axes; the periodic config runs 256x257."""
    cases = [
        dict(nx=257, ny=257, periodic=False),
        dict(nx=256, ny=257, periodic=True),
    ]
    for case in cases:
        def build(mesh):
            model = Navier2D(
                case["nx"], case["ny"], 1e5, 1.0, 1e-3, 1.0, "rbc",
                periodic=case["periodic"], mesh=mesh,
            )
            model.set_velocity(0.1, 1.0, 1.0)
            model.set_temperature(0.1, 1.0, 1.0)
            return model

        serial = build(None)
        sharded = build(make_mesh())
        serial.update_n(3)
        sharded.update_n(3)
        for attr in ("temp", "velx", "vely", "pres", "pseu"):
            np.testing.assert_allclose(
                np.asarray(getattr(sharded.state, attr)),
                np.asarray(getattr(serial.state, attr)),
                atol=1e-11,
                err_msg=f"{case}: {attr}",
            )


def test_sharded_sep_layout_matches_serial(monkeypatch):
    """The parity-separated layout + its fast-key step paths under the pencil
    mesh (what a real multi-chip TPU runs: FORCE_TPU_PATH selects matmul
    transforms, sep auto-engages) — sharded == serial."""
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")

    def build(mesh):
        model = Navier2D(33, 32, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False, mesh=mesh)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        assert all(model.temp_space.sep)  # the layout under test is active
        return model

    serial = build(None)
    sharded = build(make_mesh())
    serial.update_n(5)
    sharded.update_n(5)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(sharded.state, attr)),
            np.asarray(getattr(serial.state, attr)),
            atol=1e-12,
            err_msg=attr,
        )


@pytest.mark.slow
def test_sharded_split_periodic_mixed_sep_matches_serial(monkeypatch):
    """The REAL multi-chip periodic path: split Re/Im Fourier x Chebyshev
    with the Chebyshev axis in the sep layout (the at-scale periodic1024
    candidate) — sharded == serial through the MANUAL-sharding step.

    De-xfailed: the fused step now runs the convection chain, the
    convection-velocity syntheses and the pressure-Poisson fast-diag solve
    (the stage the miscompile bisects to) as manually-partitioned shard_map
    regions with hand-placed transposes (parallel/decomp.ShardedConv/
    ShardedSynthesis/ShardedPoisson), sidestepping the broken GSPMD
    propagation by construction.  The upstream bug itself is still tracked
    by the pinned RUSTPDE_FORCE_FUSED_GSPMD=1 sibling below."""
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    monkeypatch.setenv("RUSTPDE_SEP", "1")
    monkeypatch.delenv("RUSTPDE_FORCE_FUSED_GSPMD", raising=False)

    def build(mesh):
        model = Navier2D(16, 17, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=True, mesh=mesh)
        assert model.temp_space.bases[0].kind.is_split
        assert model.temp_space.sep == (False, True)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        return model

    serial = build(None)
    sharded = build(make_mesh())
    assert sharded._split_sep_mode() == "manual"
    assert sharded._manual_poisson is not None
    serial.update_n(8)
    sharded.update_n(8)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(sharded.state, attr)),
            np.asarray(getattr(serial.state, attr)),
            atol=1e-12,
            err_msg=attr,
        )
    assert sharded.eval_nu() == pytest.approx(serial.eval_nu(), abs=1e-12)


@pytest.mark.xfail(
    reason="XLA GSPMD regression (container jax 0.4.37), pinned: the fully "
    "fused split-sep periodic step under GSPMD alone miscompiles — every "
    "stage matches serial to ~1e-17 jitted separately, and the bisection in "
    "parallel/decomp.ShardedPoisson localizes the break to the fused "
    "fast-diag Poisson solve on the split axis.  The default path routes "
    "that solve (plus conv/syntheses) through manual shard_map regions and "
    "is exact (test above); this sibling pins RUSTPDE_FORCE_FUSED_GSPMD=1 "
    "so the upstream bug keeps being tracked — it XPASSES once a fixed jax "
    "lands, at which point the manual default can be re-benchmarked.",
    strict=False,
)
@pytest.mark.slow
def test_sharded_split_periodic_fused_gspmd_pinned(monkeypatch):
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    monkeypatch.setenv("RUSTPDE_SEP", "1")
    monkeypatch.setenv("RUSTPDE_FORCE_FUSED_GSPMD", "1")

    def build(mesh):
        model = Navier2D(16, 17, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=True, mesh=mesh)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        return model

    serial = build(None)
    sharded = build(make_mesh())
    assert sharded._split_sep_mode() == "fused"
    serial.update_n(8)
    sharded.update_n(8)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(sharded.state, attr)),
            np.asarray(getattr(serial.state, attr)),
            atol=1e-12,
            err_msg=attr,
        )


def test_sharded_split_periodic_manual_guard(monkeypatch):
    """The runtime guard now PREFERS the manual path: a split-sep periodic
    model under an active mesh routes conv/syntheses/Poisson through the
    manual shard_map regions — compiled, fused, and exact (sharded ==
    serial), with no slow-path warning."""
    import warnings

    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    monkeypatch.setenv("RUSTPDE_SEP", "1")
    monkeypatch.delenv("RUSTPDE_FORCE_FUSED_GSPMD", raising=False)
    monkeypatch.delenv("RUSTPDE_SPLIT_SEP_FALLBACK", raising=False)

    def build(mesh):
        model = Navier2D(16, 17, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=True, mesh=mesh)
        assert model.temp_space.bases[0].kind.is_split
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        return model

    serial = build(None)  # no mesh: fused fast path, guard inactive
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # no fallback warning
        sharded = build(make_mesh())
    assert sharded._split_sep_mode() == "manual"
    assert sharded._conv_impl is not None and sharded._manual_poisson is not None
    serial.update_n(3)
    sharded.update_n(3)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(sharded.state, attr)),
            np.asarray(getattr(serial.state, attr)),
            atol=1e-12,
            err_msg=attr,
        )
    assert sharded.eval_nu() == pytest.approx(serial.eval_nu(), abs=1e-12)


def test_sharded_split_periodic_eager_pin(monkeypatch):
    """RUSTPDE_SPLIT_SEP_FALLBACK=eager keeps the old per-stage path
    reachable for triage A/Bs, with its one-time warning."""
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    monkeypatch.setenv("RUSTPDE_SEP", "1")
    monkeypatch.setenv("RUSTPDE_SPLIT_SEP_FALLBACK", "eager")
    monkeypatch.delenv("RUSTPDE_FORCE_FUSED_GSPMD", raising=False)
    monkeypatch.setattr(Navier2D, "_warned_split_sep_fallback", False)

    def build(mesh):
        model = Navier2D(16, 17, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=True, mesh=mesh)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        return model

    serial = build(None)
    with pytest.warns(RuntimeWarning, match="per-stage"):
        sharded = build(make_mesh())
    assert sharded._split_sep_mode() == "eager"
    serial.update_n(3)
    sharded.update_n(3)
    np.testing.assert_allclose(
        np.asarray(sharded.state.temp), np.asarray(serial.state.temp), atol=1e-12
    )


@pytest.mark.slow
def test_sharded_split_periodic_manual_ensemble(monkeypatch):
    """The ensemble engine rides the manual path too: vmapping the step
    jaxpr batches THROUGH the shard_map regions (vmap-of-shard_map) —
    sharded == serial, no per-member eager dispatch."""
    from rustpde_mpi_tpu import NavierEnsemble

    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    monkeypatch.setenv("RUSTPDE_SEP", "1")
    monkeypatch.delenv("RUSTPDE_FORCE_FUSED_GSPMD", raising=False)

    def build(mesh):
        model = Navier2D(16, 17, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=True, mesh=mesh)
        return NavierEnsemble.from_seeds(model, seeds=range(2))

    serial = build(None)
    sharded = build(make_mesh())
    serial.update_n(2)
    sharded.update_n(2)
    assert sharded.alive().all()
    np.testing.assert_allclose(
        np.asarray(sharded.state.temp), np.asarray(serial.state.temp), atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(sharded.eval_nu()), np.asarray(serial.eval_nu()), atol=1e-12
    )
