"""Four-step (Bailey) MXU FFT/DCT factorization (ops/fourstep.py).

The factored transforms must be numerically interchangeable with the dense
transform matrices (1e-12 absolute in f64 — same reductions, reassociated)
on even and odd lengths, prime-free and not, along both axes, and through
the Base/Space wrappers that auto-select them.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from rustpde_mpi_tpu.ops import chebyshev as chb
from rustpde_mpi_tpu.ops import fourier as fou
from rustpde_mpi_tpu.ops import fourstep


def _dev(m):
    return jnp.asarray(m)


@pytest.mark.parametrize("n", [16, 24, 36, 128, 510])
def test_rfft_plans_match_numpy(n):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 3))
    m = n // 2 + 1
    c = np.fft.rfft(x, axis=0)
    plan = fourstep.RfftPlan(n, _dev)
    got = np.asarray(plan.split(jnp.asarray(x)))
    np.testing.assert_allclose(got[:m], c.real, atol=1e-12)
    np.testing.assert_allclose(got[m:], c.imag, atol=1e-12)
    np.testing.assert_allclose(np.asarray(plan.re(jnp.asarray(x))), c.real, atol=1e-12)
    # inverse: split coefficients in the amplitude convention c/n
    s = jnp.asarray(np.concatenate([c.real, c.imag], axis=0) / n)
    v = np.asarray(fourstep.IrfftPlan(n, _dev).apply(s))
    np.testing.assert_allclose(v, x, atol=1e-12)


@pytest.mark.parametrize("n", [16, 36, 128])
def test_c2c_plans_match_numpy(n):
    rng = np.random.default_rng(1)
    z = rng.standard_normal((n, 4)) + 1j * rng.standard_normal((n, 4))
    fwd = fourstep.C2cPlan(n, _dev, sign=-1.0)
    re, im = fwd.apply(jnp.asarray(z.real), jnp.asarray(z.imag))
    zf = np.fft.fft(z, axis=0)
    np.testing.assert_allclose(np.asarray(re), zf.real, atol=1e-11)
    np.testing.assert_allclose(np.asarray(im), zf.imag, atol=1e-11)
    bwd = fourstep.C2cPlan(n, _dev, sign=+1.0)
    re, im = bwd.apply(jnp.asarray(z.real), jnp.asarray(z.imag))
    zi = np.fft.ifft(z, axis=0) * n
    np.testing.assert_allclose(np.asarray(re), zi.real, atol=1e-11)
    np.testing.assert_allclose(np.asarray(im), zi.imag, atol=1e-11)


def test_dense_vs_fourstep_equality_dense_sizes():
    """VERDICT r2 'done' criterion: factored == dense transform at 1e-12
    (f64) on representative transform sizes, both matrix families."""
    rng = np.random.default_rng(2)
    for n in (64, 96, 256):
        x = rng.standard_normal((n, 2))
        dense = fou.split_forward_matrix(n) @ x
        got = np.asarray(fourstep.RfftPlan(n, _dev).split(jnp.asarray(x))) / n
        np.testing.assert_allclose(got, dense, atol=1e-12)
        s = rng.standard_normal((2 * (n // 2 + 1), 2))
        dense_b = fou.split_backward_matrix(n) @ s
        got_b = np.asarray(fourstep.IrfftPlan(n, _dev).apply(jnp.asarray(s)))
        np.testing.assert_allclose(got_b, dense_b, atol=1e-11)


def test_f32_accuracy():
    """f32 factored transform tracks the f64 dense one to ~1e-5 relative
    (better than the dense f32 GEMM's own roundoff profile)."""
    rng = np.random.default_rng(3)
    n = 256
    x64 = rng.standard_normal((n, 4))
    ref = fou.split_forward_matrix(n) @ x64
    to_f32 = lambda m: jnp.asarray(np.asarray(m, dtype=np.float32))  # noqa: E731
    got = np.asarray(fourstep.RfftPlan(n, to_f32).split(to_f32(x64))) / n
    scale = np.abs(ref).max()
    assert np.abs(got - ref).max() / scale < 2e-5


@pytest.fixture
def force_fourstep(monkeypatch):
    monkeypatch.setattr(fourstep, "_MODE", "1")


@pytest.mark.parametrize("n", [33, 34, 37])
def test_base_fast_cheb_matches_dense(force_fourstep, n):
    """Base-level matmul transforms ride the fast DCT when enabled and match
    the dense operator matrices exactly."""
    from rustpde_mpi_tpu import bases

    rng = np.random.default_rng(4)
    for ctor in (bases.chebyshev, bases.cheb_dirichlet, bases.cheb_neumann):
        base = ctor(n)
        assert base._dct_plan is not None
        v = rng.standard_normal((n, 5))
        if base.kind == bases.BaseKind.CHEBYSHEV:
            F = base.projection @ chb.analysis_matrix(n)
            got = np.asarray(base.forward(jnp.asarray(v), 0, "matmul"))
            np.testing.assert_allclose(got, F @ v, atol=1e-12)
        S = chb.synthesis_matrix(n) @ base.stencil
        c = rng.standard_normal((base.m, 5))
        got = np.asarray(base.backward(jnp.asarray(c), 0, "matmul"))
        np.testing.assert_allclose(got, S @ c, atol=1e-12)
        # axis-1 application through the moveaxis wrapper
        got1 = np.asarray(base.backward(jnp.asarray(c.T), 1, "matmul"))
        np.testing.assert_allclose(got1, (S @ c).T, atol=1e-12)
        o = rng.standard_normal((n, 5))
        got_o = np.asarray(base.backward_ortho(jnp.asarray(o), 0, "matmul"))
        np.testing.assert_allclose(got_o, chb.synthesis_matrix(n) @ o, atol=1e-12)


def test_split_base_fast_matches_matrices(force_fourstep):
    from rustpde_mpi_tpu import bases

    n = 36
    base = bases.fourier_r2c_split(n)
    assert base._rfft_plan is not None
    rng = np.random.default_rng(5)
    v = rng.standard_normal((n, 3))
    np.testing.assert_allclose(
        np.asarray(base.forward(jnp.asarray(v), 0)),
        fou.split_forward_matrix(n) @ v,
        atol=1e-13,
    )
    s = rng.standard_normal((base.m, 3))
    np.testing.assert_allclose(
        np.asarray(base.backward(jnp.asarray(s), 0)),
        fou.split_backward_matrix(n) @ s,
        atol=1e-12,
    )
    # round trip through a Space1-style use
    np.testing.assert_allclose(
        np.asarray(base.backward(base.forward(jnp.asarray(v), 0), 0)), v, atol=1e-12
    )


def test_biperiodic_fast_matches_fft(force_fourstep):
    from rustpde_mpi_tpu.bases import BiPeriodicSpace2

    sp = BiPeriodicSpace2(32, 36, method="matmul")
    spf = BiPeriodicSpace2(32, 36, method="fft")
    assert sp._x_c2c_fwd is not None and sp._y_rfft_plan is not None
    rng = np.random.default_rng(6)
    v = rng.standard_normal((32, 36))
    a = np.asarray(sp.forward(jnp.asarray(v)))
    b = np.asarray(spf.forward(jnp.asarray(v)))
    np.testing.assert_allclose(a, b, atol=1e-13)
    np.testing.assert_allclose(np.asarray(sp.backward(jnp.asarray(a))), v, atol=1e-12)


@pytest.mark.slow
def test_navier_step_fast_vs_dense_transforms():
    """One full confined Navier2D step with the four-step transforms forced on
    matches the dense-transform step to near machine epsilon (the grid is
    below the auto gate, so default stays dense)."""
    import subprocess
    import sys
    import os
    import json

    code = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %r)
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from rustpde_mpi_tpu import Navier2D
m = Navier2D.new_confined(33, 33, 1e6, 1.0, 1e-3, 1.0, "rbc")
m.update_n(5)
print("OUT:" + json.dumps({
    "nu": m.eval_nu(), "t": np.asarray(m.state.temp).tolist()}))
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = {}
    for mode in ("1", "0"):
        env = dict(
            os.environ,
            RUSTPDE_X64="1",
            RUSTPDE_FOURSTEP=mode,
            RUSTPDE_FORCE_TPU_PATH="1",
            RUSTPDE_FAST_DERIV="1" if mode == "1" else "0",
        )
        res = subprocess.run(
            [sys.executable, "-c", code % repo],
            capture_output=True,
            text=True,
            env=env,
            timeout=600,
        )
        line = [ln for ln in res.stdout.splitlines() if ln.startswith("OUT:")]
        assert line, res.stderr[-2000:]
        results[mode] = json.loads(line[0][4:])
    np.testing.assert_allclose(
        np.asarray(results["1"]["t"]), np.asarray(results["0"]["t"]), atol=1e-11
    )
    assert abs(results["1"]["nu"] - results["0"]["nu"]) < 1e-9
