"""Golden parity tests against the reference's embedded pypde solutions.

The reference hard-codes solution arrays produced by the author's independent
Python implementation ("pypde") with tolerance 1e-3
(/root/reference/src/solver/poisson.rs:287-291 "Python (pypde's) solution",
/root/reference/src/solver/hholtz_adi.rs:203-211).  Matching them pins this
framework to the reference's *exact discrete systems* — including the
truncated quasi-inverse convention (ops/chebyshev.quasi_inverse_b2) these
goldens identified — not merely the same continuous equations.

Also asserts FastDiag == TensorSolver to machine precision: the TPU (pure
GEMM) and CPU (banded scan) execution paths solve the identical discrete
system.
"""

import numpy as np
import pytest

from rustpde_mpi_tpu import Space2, cheb_dirichlet, cheb_neumann, fourier_r2c
from rustpde_mpi_tpu.ops import chebyshev as chb
from rustpde_mpi_tpu.solver import HholtzAdi, Hholtz, Poisson

# tolerance of the reference's approx_eq (poisson.rs:254)
TOL = 1e-3

# /root/reference/src/solver/hholtz_adi.rs:193-211 test_hholtz_adi (1-D, n=7)
GOLD_HHOLTZ_1D = np.array(
    [-0.08214845, -0.10466761, -0.06042153, 0.04809052, 0.04082296]
)

# /root/reference/src/solver/hholtz_adi.rs:215-246 test_hholtz2d_adi (7x7)
GOLD_HHOLTZ_2D = np.array(
    [
        [-7.083e-03, -9.025e-03, -5.210e-03, 4.146e-03, 3.520e-03],
        [5.809e-04, 7.402e-04, 4.273e-04, -3.401e-04, -2.887e-04],
        [1.699e-04, 2.165e-04, 1.250e-04, -9.951e-05, -8.447e-05],
        [-1.007e-03, -1.283e-03, -7.406e-04, 5.895e-04, 5.004e-04],
        [-6.775e-04, -8.632e-04, -4.983e-04, 3.966e-04, 3.366e-04],
    ]
)

# /root/reference/src/solver/poisson.rs:275-292 test_poisson1d (n=8)
GOLD_POISSON_1D = np.array([0.1042, 0.0809, 0.0625, 0.0393, -0.0417, -0.0357])

# /root/reference/src/solver/poisson.rs:295-326 test_poisson2d (8x7)
GOLD_POISSON_2D = np.array(
    [
        [0.01869736, 0.0244178, 0.01403203, -0.0202917, -0.0196697],
        [-0.0027890, -0.004035, -0.0059870, -0.0023490, -0.0046850],
        [-0.0023900, -0.007947, -0.0085570, -0.0189310, -0.0223680],
        [-0.0038940, -0.006622, -0.0096270, -0.0079020, -0.0120490],
        [0.00025400, -0.006752, -0.0082940, -0.0316230, -0.0361640],
        [-0.0001120, -0.004374, -0.0066430, -0.0216410, -0.0262570],
    ]
)


def _ops_1d(n):
    """The reference's per-axis preconditioned matrices."""
    S = chb.stencil_dirichlet(n)
    peye = chb.restricted_eye(n)
    pinv = peye @ chb.quasi_inverse_b2(n)
    return S, peye, pinv


def test_golden_hholtz_1d():
    """(I - D2) u = B2 f on cheb_dirichlet(7), f_k = k+1."""
    n = 7
    S, peye, pinv = _ops_1d(n)
    b = np.arange(1.0, n + 1.0)
    x = np.linalg.solve(pinv @ S - peye @ S, pinv @ b)
    np.testing.assert_allclose(x, GOLD_HHOLTZ_1D, atol=TOL)


def test_golden_poisson_1d():
    """D2 u = B2 f on cheb_dirichlet(8), f_k = k+1."""
    n = 8
    S, peye, pinv = _ops_1d(n)
    b = np.arange(1.0, n + 1.0)
    x = np.linalg.solve(peye @ S, pinv @ b)
    np.testing.assert_allclose(x, GOLD_POISSON_1D, atol=TOL)


@pytest.mark.parametrize("method", ["banded", "fd"])
def test_golden_hholtz2d_adi(method):
    space = Space2(cheb_dirichlet(7), cheb_dirichlet(7))
    b = np.tile(np.arange(1.0, 8.0), (7, 1))
    if method == "banded":
        solver = HholtzAdi(space, (1.0, 1.0), method="banded")
        x = np.asarray(solver.solve(b))
    else:
        # the dense path solves the same ADI system
        solver = HholtzAdi(space, (1.0, 1.0), method="dense")
        x = np.asarray(solver.solve(b))
    np.testing.assert_allclose(x, GOLD_HHOLTZ_2D, atol=TOL)


@pytest.mark.parametrize("method", ["banded", "fd"])
def test_golden_poisson2d(method):
    space = Space2(cheb_dirichlet(8), cheb_dirichlet(7))
    b = np.tile(np.arange(1.0, 8.0), (8, 1))
    solver = Poisson(space, (1.0, 1.0), method=method)
    x = np.asarray(solver.solve(b))
    np.testing.assert_allclose(x, GOLD_POISSON_2D, atol=TOL)


def test_golden_poisson2d_complex():
    """Complex rhs variant (poisson.rs:328-363): solve(re) + i*solve(im)."""
    space = Space2(cheb_dirichlet(8), cheb_dirichlet(7))
    b = np.tile(np.arange(1.0, 8.0), (8, 1)).astype(np.complex128)
    b = b + 1j * b.real
    solver = Poisson(space, (1.0, 1.0), method="banded")
    x = np.asarray(solver.solve(b))
    np.testing.assert_allclose(x.real, GOLD_POISSON_2D, atol=TOL)
    np.testing.assert_allclose(x.imag, GOLD_POISSON_2D, atol=TOL)


@pytest.mark.parametrize(
    "bx,by,c,alpha,cls",
    [
        ("dirichlet", "dirichlet", (1.0, 1.0), "hholtz", Hholtz),
        ("neumann", "neumann", (1.0, 1.0), "poisson", Poisson),
        ("fourier", "dirichlet", (0.7, 1.3), "hholtz", Hholtz),
        ("fourier", "neumann", (1.0, 1.0), "poisson", Poisson),
    ],
)
def test_fastdiag_equals_tensorsolver(bx, by, c, alpha, cls):
    """The TPU path (FastDiag, pure GEMMs) and the CPU path (TensorSolver,
    banded scans) must produce the same discrete solution to ~machine
    precision — they diagonalize the same preconditioned pencils."""
    mk = {"dirichlet": cheb_dirichlet, "neumann": cheb_neumann, "fourier": fourier_r2c}
    nx, ny = 16, 11
    space = Space2(mk[bx](nx), mk[by](ny))
    rng = np.random.default_rng(42)
    b = rng.standard_normal((nx if bx != "fourier" else nx, ny))
    bhat = np.asarray(space.forward(b))
    rhs = np.asarray(space.to_ortho(bhat))
    x_banded = np.asarray(cls(space, c, method="banded").solve(rhs))
    x_fd = np.asarray(cls(space, c, method="fd").solve(rhs))
    scale = max(np.abs(x_banded).max(), 1e-30)
    np.testing.assert_allclose(x_fd, x_banded, atol=1e-10 * scale, rtol=1e-9)
