"""Statistics + vorticity post-processing tests (SURVEY.md S2 rows
`statistics`, `vorticity`)."""

import numpy as np
import pytest

from rustpde_mpi_tpu import Navier2D, Statistics, integrate, vorticity_auto

h5py = pytest.importorskip("h5py")


def _model(periodic=False, nx=16):
    model = Navier2D(
        nx if periodic else 17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=periodic
    )
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    return model


def test_running_average_weighting():
    """(avg*n + new)/(n+1): after updates at two different states, the
    average equals the mean of the sampled fields (statistics.rs:84-108)."""
    model = _model()
    stats = Statistics(model, save_stat=0.01, write_stat=1.0)

    samples = []
    for _ in range(3):
        model.update_n(5)
        with model._scope():
            samples.append(np.asarray(model.temp_space.to_ortho(model.state.temp)))
        stats.update(model)
    assert stats.num_save == 3
    np.testing.assert_allclose(stats.t_avg, np.mean(samples, axis=0), atol=1e-13)
    # created at t=0, so the averaging window spans the whole run
    assert stats.avg_time == pytest.approx(model.time)
    assert stats.tot_time == pytest.approx(model.time)


def test_update_ignores_time_regression():
    model = _model()
    stats = Statistics(model, 0.01, 1.0)
    model.update_n(5)
    stats.update(model)
    n = stats.num_save
    model.time -= 1.0  # simulate a mismatched restart
    stats.update(model)
    assert stats.num_save == n  # rejected, like the reference


def test_statistics_write_read_roundtrip(tmp_path):
    model = _model()
    stats = Statistics(model, 0.01, 1.0)
    model.update_n(10)
    stats.update(model)
    fname = str(tmp_path / "statistics.h5")
    stats.write(fname)

    with h5py.File(fname, "r") as h5:
        for var in ("temp", "ux", "uy", "nusselt"):
            for ds in ("x", "y", "v", "vhat"):
                assert f"{var}/{ds}" in h5
        for key in ("tot_time", "avg_time", "num_save", "ra", "ka"):
            assert key in h5

    other = Statistics(model, 0.01, 1.0)
    other.read(fname)
    assert other.num_save == stats.num_save
    assert other.tot_time == pytest.approx(stats.tot_time)
    np.testing.assert_allclose(other.t_avg, stats.t_avg, atol=1e-14)
    np.testing.assert_allclose(other.nusselt, stats.nusselt, atol=1e-14)


def test_nusselt_field_volume_average_matches_nuvol():
    """The volume average of the pointwise Nusselt field equals eval_nuvol
    (same integrand) for a single-sample average."""
    model = _model()
    model.update_n(20)
    stats = Statistics(model, 0.01, 1.0)
    stats.update(model)
    sp = model.field_space
    nu_v = np.asarray(sp.backward_ortho(np.asarray(stats.nusselt)))
    w0 = np.asarray(model._w0)
    w1 = np.asarray(model._w1)
    vol_avg = float((nu_v * w0[:, None] * w1[None, :]).sum())
    # dealiasing of the stored field perturbs the mean slightly
    assert vol_avg == pytest.approx(model.eval_nuvol(), rel=2e-2, abs=1e-3)


@pytest.mark.slow
def test_callback_integration_writes_statistics(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    model = _model()
    model.statistics = Statistics(model, save_stat=0.05, write_stat=0.1)
    model.write_intervall = 10.0  # suppress flow snapshots
    integrate(model, 0.2, save_intervall=0.05)
    assert model.statistics.num_save >= 3
    assert (tmp_path / "data" / "statistics.h5").exists()


@pytest.mark.parametrize("periodic", [False, True])
def test_vorticity_appends_to_snapshot(tmp_path, periodic):
    model = _model(periodic=periodic)
    model.update_n(10)
    fname = str(tmp_path / "flow.h5")
    model.write(fname)
    vorticity_auto(fname)
    with h5py.File(fname, "r") as h5:
        assert "vorticity/v" in h5
        vort = np.asarray(h5["vorticity/v"])
    assert vort.shape == model.field_space.shape_physical
    assert np.all(np.isfinite(vort))
    # cross-check against a direct spectral computation of dv/dx - du/dy
    with model._scope():
        dvdx = model.vely_space.gradient(model.state.vely, (1, 0), (1.0, 1.0))
        dudz = model.velx_space.gradient(model.state.velx, (0, 1), (1.0, 1.0))
        direct = np.asarray(model.field_space.backward_ortho(dvdx - dudz))
    # stored field is dealiased; compare on the interior spectrum via a loose
    # physical-space tolerance
    assert np.abs(vort - direct).max() / max(np.abs(direct).max(), 1e-30) < 0.2
    # tiny test grids lose a visible spectral fraction to the 2/3 cut, so the
    # correlation bound is loose; the shape comparison is the real check
    assert np.corrcoef(vort.ravel(), direct.ravel())[0, 1] > 0.99
