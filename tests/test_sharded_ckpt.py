"""Sharded two-phase checkpoint tests (utils/checkpoint.py distributed
layer) on the single-process virtual CPU mesh: write/verify/restore
roundtrips, the topology-elastic restore matrix, corrupt/missing-shard
rejection with fallback, shard-set rotation, the runner's sharded
blocking-vs-overlapped digest identity, and host-scoped fault parsing.
The true multi-*process* legs (one host killed between shard fsync and
manifest commit) live in tests/test_multiprocess.py."""

import json
import os

import jax
import numpy as np
import pytest

from rustpde_mpi_tpu import NavierEnsemble, ResilientRunner
from rustpde_mpi_tpu.config import IOConfig
from rustpde_mpi_tpu.parallel.mesh import make_mesh
from rustpde_mpi_tpu.utils import checkpoint as cp
from rustpde_mpi_tpu.utils.resilience import FaultPlan

h5py = pytest.importorskip("h5py")

_FIELDS = ("temp", "velx", "vely", "pres", "pseu")


# shared tier-wide builders (model_builders.py): every jit shape here is
# already compiled by test_io_pipeline/test_resilience earlier in the same
# pytest process, so these tests add no fresh compile time to the tier-1
# budget
from model_builders import build_rbc17 as _build17
from model_builders import build_rbc33 as _build


def _assert_state_equal(a, b, exact=True, atol=1e-12):
    for name in _FIELDS:
        x, y = np.asarray(getattr(a.state, name)), np.asarray(getattr(b.state, name))
        if exact:
            np.testing.assert_array_equal(x, y, err_msg=name)
        else:
            np.testing.assert_allclose(x, y, atol=atol, err_msg=name)


@pytest.fixture(scope="module")
def written(tmp_path_factory):
    """One stepped mesh model + its committed sharded checkpoint, shared by
    the restore-matrix and rejection tests.  The full 8-device mesh is the
    exact jit shape test_parallel.py already compiled earlier in this
    pytest process, so the fixture costs no fresh step compile."""
    run_dir = str(tmp_path_factory.mktemp("sharded"))
    model = _build(mesh=make_mesh())
    model.update_n(4)
    path = cp.checkpoint_path(run_dir, 4)
    stats = cp.write_sharded_snapshot(model, path, step=4)
    return model, path, stats


def test_sharded_write_commits_manifest_and_verifies(written):
    model, path, stats = written
    assert stats["ok"] and stats["shards"] == 1 and stats["bytes_host"] > 0
    attrs = cp.verify_snapshot(path)  # manifest + shard digests end-to-end
    assert int(attrs["step"]) == 4
    assert int(attrs["sharded"]) == 1
    assert attrs["dt"] == pytest.approx(model.dt)
    assert cp.checkpoint_shard_files(path), "shard set must exist"
    # the manifest records the shard map with per-shard digests
    with h5py.File(path) as h5:
        meta = json.loads(h5["sharded_manifest"][()])
    assert [s["process"] for s in meta["shards"]] == [0]
    with h5py.File(cp.checkpoint_shard_files(path)[0]) as sh:
        assert sh.attrs["digest"] == meta["shards"][0]["digest"]
    assert set(meta["datasets"]) == {f"state/{f}" for f in _FIELDS}


def test_elastic_restore_matrix(written):
    """ISSUE acceptance: a checkpoint written sharded under one mesh
    restores onto serial and differently-shaped/ordered meshes, state equal
    to 1e-12 (in fact bit-equal).  Restore never compiles a step — the
    targets only place assembled slabs — so the matrix is cheap."""
    model, path, _ = written
    devs = jax.devices()
    for label, target in (
        ("serial", _build()),
        ("mesh4", _build(mesh=make_mesh(devs[:4]))),
        ("mesh_reversed", _build(mesh=make_mesh(list(reversed(devs[:2]))))),
    ):
        target.read(path)
        _assert_state_equal(model, target)
        assert target.time == model.time, label
    # (post-restore stepping equality across topologies is proven by the
    # slow-tier 2-process tests, tests/test_multiprocess.py — no extra
    # mesh-step compiles in tier-1)


def test_serial_written_sharded_restores_onto_mesh(tmp_path):
    """The reverse direction: force-sharded serial writer -> mesh reader."""
    model = _build()
    model.update_n(3)
    path = cp.checkpoint_path(str(tmp_path), 3)
    cp.write_sharded_snapshot(model, path, step=3)
    target = _build(mesh=make_mesh(jax.devices()[:2]))
    target.read(path)
    _assert_state_equal(model, target)


def test_ensemble_sharded_roundtrip(tmp_path):
    """Batched (leading-K) state leaves through the sharded format — the
    17^2 serial shapes reuse test_ensemble.py's compiled entry points; the
    mesh coverage for slab extraction/assembly lives in the single-run
    matrix tests above (the slab machinery is rank-agnostic)."""
    ens = NavierEnsemble.from_seeds(_build17(), seeds=range(3))
    ens.update_n(4)
    path = cp.checkpoint_path(str(tmp_path), 4)
    cp.write_sharded_snapshot(ens, path, step=4)
    cp.verify_snapshot(path)
    ens2 = NavierEnsemble.from_seeds(_build17(), seeds=range(3))
    ens2.read(path)
    for name in _FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ens.state, name)),
            np.asarray(getattr(ens2.state, name)),
            err_msg=name,
        )
    assert (np.asarray(ens2.steps_done) == np.asarray(ens.steps_done)).all()
    assert (ens2.alive() == ens.alive()).all()
    # K mismatch is rejected with ITS message (sharded restore is exact,
    # not K-elastic) — not the shape gate's interpolation advice
    ens1 = NavierEnsemble.from_seeds(_build17(), seeds=range(2))
    with pytest.raises(cp.CheckpointError, match="members"):
        ens1.read(path)


def test_corrupt_or_missing_shard_rejects_whole_checkpoint(tmp_path):
    model = _build()
    model.update_n(2)
    good = cp.checkpoint_path(str(tmp_path), 2)
    cp.write_sharded_snapshot(model, good, step=2)
    model.update_n(2)
    bad = cp.checkpoint_path(str(tmp_path), 4)
    cp.write_sharded_snapshot(model, bad, step=4)
    shard = cp.checkpoint_shard_files(bad)[0]
    with h5py.File(shard, "r+") as h5:
        grp = h5["state/temp"]
        name = next(iter(grp))
        grp[name][(0,) * grp[name].ndim] = 1e9  # bit rot inside one slab
    with pytest.raises(cp.CheckpointError, match="shard .* digest mismatch"):
        cp.verify_snapshot(bad)
    with pytest.raises(cp.CheckpointError):
        _build().read(bad)
    # resume falls back to the previous digest-clean checkpoint
    assert cp.latest_checkpoint(str(tmp_path)) == good
    os.remove(shard)
    with pytest.raises(cp.CheckpointError, match="missing shard"):
        cp.verify_snapshot(bad)
    # a manifest-less shard set (the aborted-commit shape) is invisible
    os.remove(bad)
    assert cp.latest_checkpoint(str(tmp_path)) == good


def test_resolution_and_dtype_mismatch_rejected(written):
    _, path, _ = written
    other = _build(nx=17, ny=17)
    with pytest.raises(cp.CheckpointError, match="resolution-fixed"):
        other.read(path)


def test_rotation_removes_shard_sets_and_orphans(tmp_path):
    model = _build()
    model.update_n(1)
    for step in range(5):
        cp.write_sharded_snapshot(
            model, cp.checkpoint_path(str(tmp_path), step), step=step
        )
    # an aborted two-phase attempt: shards without a manifest at step 0
    orphan = cp.shard_path(cp.checkpoint_path(str(tmp_path), 0), 7)
    open(orphan, "w").close()
    os.remove(cp.checkpoint_path(str(tmp_path), 0))
    removed = cp.rotate_checkpoints(str(tmp_path), keep=2)
    assert [os.path.basename(p) for p in removed] == [
        "ckpt_0000000001.h5",
        "ckpt_0000000002.h5",
    ]
    names = sorted(os.listdir(str(tmp_path)))
    # the kept window is manifests 3,4 plus their shard sets — nothing else
    assert names == [
        "ckpt_0000000003.h5",
        "ckpt_0000000003.h5.shard0",
        "ckpt_0000000004.h5",
        "ckpt_0000000004.h5.shard0",
    ]
    for step in (3, 4):
        cp.verify_snapshot(cp.checkpoint_path(str(tmp_path), step))


def _events(run_dir):
    with open(os.path.join(run_dir, "journal.jsonl"), encoding="utf-8") as fh:
        return [json.loads(line) for line in fh]


def test_runner_sharded_overlapped_matches_blocking(tmp_path):
    """ISSUE acceptance: blocking vs overlapped sharded runs produce
    byte-identical manifests and shards (content digests), the overlapped
    leg journals async sharded cadence commits, and every barrier is
    preceded by a writer drain (the commit itself fails loudly otherwise).
    The sharded format is forced on a serial model — the two-phase
    protocol is process-count-agnostic, and 17^2 serial adds no compiles."""

    def run(io, sub):
        run_dir = str(tmp_path / sub)
        runner = ResilientRunner(
            _build17(),
            max_time=0.2,
            save_intervall=0.05,
            run_dir=run_dir,
            checkpoint_every_s=None,
            checkpoint_every_t=0.05,
            io=io,
        )
        summary = runner.run()
        assert summary["outcome"] == "done"
        return summary, run_dir

    s_async, rd_async = run(IOConfig(sharded_checkpoints=True), "overlapped")
    s_block, rd_block = run(
        IOConfig(
            async_checkpoints=False,
            overlap_dispatch=False,
            diag_lag=0,
            sharded_checkpoints=True,
        ),
        "blocking",
    )
    assert s_async["nu"] == s_block["nu"]
    # manifests byte-identical (content digests) ...
    da = cp.verify_snapshot(s_async["checkpoint"])
    db = cp.verify_snapshot(s_block["checkpoint"])
    assert da["digest"] == db["digest"]
    # ... and every shard byte-identical too
    shards_a = cp.checkpoint_shard_files(s_async["checkpoint"])
    shards_b = cp.checkpoint_shard_files(s_block["checkpoint"])
    assert len(shards_a) == len(shards_b) == 1
    for fa, fb in zip(shards_a, shards_b):
        with h5py.File(fa) as a, h5py.File(fb) as b:
            assert a.attrs["digest"] == b.attrs["digest"]
    ev = _events(rd_async)
    async_commits = [
        e for e in ev if e.get("checkpoint_sharded") and e.get("async")
    ]
    assert len(async_commits) >= 1, [e["event"] for e in ev]
    row = async_commits[0]["checkpoint_sharded"]
    assert row["shards"] == 1 and row["bytes_host"] > 0 and "barrier_s" in row
    start = next(e for e in ev if e["event"] == "start")
    assert start["io"]["sharded_checkpoints"] is True
    assert not any(e["event"] == "checkpoint_failed" for e in ev)


@pytest.mark.slow
def test_runner_sharded_nan_rollback_and_resume(tmp_path):
    """Divergence rollback and preempt/resume both ride the sharded format:
    the rollback target is a digest-clean manifest, and a fresh runner
    resumes from a sharded checkpoint.  Slow tier: three full runner runs
    plus a dt/2 solver rebuild — and the same paths are also driven across
    real processes by tests/test_multiprocess.py."""
    run_dir = str(tmp_path / "nan")
    runner = ResilientRunner(
        _build17(),
        max_time=0.2,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        max_retries=2,
        dt_backoff=0.5,
        fault="nan@6",
        io=IOConfig(sharded_checkpoints=True),
    )
    summary = runner.run()
    assert summary["outcome"] == "done" and summary["retries"] == 1
    assert np.isfinite(summary["nu"])
    assert cp.verify_snapshot(summary["checkpoint"])["sharded"] == 1

    run_dir = str(tmp_path / "kill")
    r1 = ResilientRunner(
        _build17(),
        max_time=0.3,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        fault="kill@12",
        io=IOConfig(sharded_checkpoints=True),
    )
    assert r1.run()["outcome"] == "preempted"
    r2 = ResilientRunner(
        _build17(),
        max_time=0.3,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        io=IOConfig(sharded_checkpoints=True),
    )
    s2 = r2.run()
    assert s2["outcome"] == "done" and s2["step"] == 30
    assert any(e["event"] == "resumed" for e in _events(run_dir))


def test_fault_spec_host_scope():
    plan = FaultPlan.from_spec("kill@10:host1")
    assert (plan.kind, plan.step, plan.host) == ("kill", 10, 1)
    plan = FaultPlan.from_spec("nan@8:host0")
    assert (plan.kind, plan.step, plan.host) == ("nan", 8, 0)
    assert FaultPlan.from_spec("nan@8").host is None
    assert plan.scoped_here()  # single process == process 0
    assert not FaultPlan.from_spec("nan@8:host3").scoped_here()
    for bad in ("nan@8:h1", "nan@8:", "kill@x:host1"):
        with pytest.raises(ValueError):
            FaultPlan.from_spec(bad)


def test_host_scoped_poison_masks_only_owned_columns():
    """A host-scoped NaN poisons only the scoped process's spectral columns
    — on a single process, host0 owns everything and host1 owns nothing."""
    from rustpde_mpi_tpu.utils.resilience import poison_state

    model = _build(mesh=make_mesh())
    model.update_n(1)
    before = np.asarray(model.state.temp).copy()
    poison_state(model, host=1)  # no such process: nothing owned, no-op
    np.testing.assert_array_equal(np.asarray(model.state.temp), before)
    poison_state(model, host=0)
    assert np.isnan(np.asarray(model.state.temp)).all()


def test_write_pencils_single_handle_and_shard_digests(tmp_path):
    """Satellites: write_pencils holds one file handle per dataset (and
    still round-trips, complex included); write_pencils_concurrent stamps
    per-shard digest attrs consistent with the checkpoint layer."""
    from rustpde_mpi_tpu.parallel.decomp import Decomp2d
    from rustpde_mpi_tpu.utils.slice_io import (
        read_slice,
        write_pencils,
        write_pencils_concurrent,
    )

    mesh = make_mesh(jax.devices()[:4])
    d = Decomp2d((12, 8), mesh)
    rng = np.random.default_rng(3)
    a = rng.standard_normal((12, 8))
    c = a + 1j * rng.standard_normal((12, 8))
    fname = str(tmp_path / "pencils.h5")
    write_pencils(fname, "v", d.place_y_pencil(a), d, pencil="y")
    np.testing.assert_array_equal(read_slice(fname, "v", (0, 0), (12, 8)), a)
    write_pencils(fname, "w", c, d, pencil="y")
    got = read_slice(fname, "w", (0, 0), (12, 8), is_complex=True)
    np.testing.assert_array_equal(got, c)
    with pytest.raises(ValueError, match="exists with shape"):
        write_pencils(fname, "v", d.place_y_pencil(np.zeros((8, 12))),
                      Decomp2d((8, 12), mesh), pencil="y")

    fname2 = str(tmp_path / "conc.h5")
    write_pencils_concurrent(fname2, "v", d.place_y_pencil(a), d, pencil="y")
    for rank in range(d.nprocs):
        shard = f"{fname2}.v.shard{rank}"
        # digest attr verifies through the checkpoint layer's machinery
        attrs = cp.verify_snapshot(shard)
        assert attrs["digest"]
