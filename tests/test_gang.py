"""Two-level serving units (parallel/submesh.py + serve/fleet/gang.py):
sub-mesh canonicalization and carving, gang-lease fate sharing — all-or-
nothing formation, the break-vs-member-renew race (exactly one winner,
tokens monotonic across gang generations), the fate-shared stale sweep —
partial-gang heartbeat aggregation, typed sub-mesh admission, gang fault
scoping, and the CI guard that the default (``submesh=None``) service
emits not one gang journal row and keeps today's bare bucket keys.

The 2-process gang campaign itself (formation, SIGKILL containment,
loss-free reclaim) runs in tests/test_multiprocess.py's slow tier and
the ``serve_submesh129`` bench leg.
"""

import os
import time

import pytest

from rustpde_mpi_tpu.config import ServeConfig, SubmeshConfig
from rustpde_mpi_tpu.parallel import submesh as sm
from rustpde_mpi_tpu.serve import SimRequest, SimServer
from rustpde_mpi_tpu.serve.fleet import gang as gg
from rustpde_mpi_tpu.serve.fleet import qos as qos
from rustpde_mpi_tpu.serve.fleet.lease import LeaseLost, LeaseManager, bucket_tag
from rustpde_mpi_tpu.serve.fleet.proxy import (
    read_replica_status,
    write_replica_heartbeat,
)
from rustpde_mpi_tpu.serve.request import AdmissionError, RequestError
from rustpde_mpi_tpu.utils.faults import FaultPlan, FaultSpecError
from rustpde_mpi_tpu.utils.journal import read_journal

pytest.importorskip("h5py")

_KEY = ("rbc", 34, 34, "1.0e4", "1.0", "0.01", 0, "f64", "none", "base", 2)


class _Dev:
    """CPU test double for a jax device: only process_index matters."""

    def __init__(self, pid):
        self.process_index = pid

    def __repr__(self):
        return f"dev(p{self.process_index})"


# -- canonicalization (pure, proxy-side) --------------------------------------


def test_grid_fits_divisibility_rule():
    assert sm.grid_fits(17, 17, 1)  # shape 1 always fits (unsharded)
    assert sm.grid_fits(34, 34, 2)  # full extent divides
    assert sm.grid_fits(130, 130, 4)  # interior (n-2) divides
    assert not sm.grid_fits(33, 33, 2)  # neither 33 nor 31 divides
    assert not sm.grid_fits(34, 33, 2)  # both dims must fit


def test_shape_for_stamps_smallest_fitting_shape():
    cfg = SubmeshConfig(shapes=(4, 2), shard_min_nx=34)
    assert sm.shape_for(17, 17, cfg) == 0  # below threshold: vmapped
    assert sm.shape_for(34, 34, cfg) == 2  # smallest fitting, not 4
    assert sm.shape_for(132, 132, cfg) == 2  # deterministic across fronts
    assert sm.shape_for(35, 35, cfg) == -1  # must shard, nothing fits


def test_serve_key_stamp_roundtrip_and_default_identity():
    bare = _KEY[:10]
    assert sm.serve_key(bare, 0) == bare  # submesh off: byte-identical
    stamped = sm.serve_key(bare, 2)
    assert len(stamped) == 11 and stamped[10] == 2
    assert sm.model_key(stamped) == bare
    assert sm.key_shape(stamped) == 2
    assert sm.key_shape(bare) == 0


# -- carving (device binding, replica-side) -----------------------------------


def test_carve_interleaves_processes_and_keeps_devices_disjoint():
    # 2 processes x 4 local devices; one 4-gang + default remainder
    devs = [_Dev(p) for p in (0, 0, 0, 0, 1, 1, 1, 1)]
    plan = sm.carve(devs, shapes=(4,), nproc=2)
    (gangsm,) = plan.submeshes
    assert gangsm.shape == 4 and plan.default.shape == 4
    # every sub-mesh takes equal devices from every process (no process
    # is ever absent from a sub-mesh collective)
    for slice_ in (gangsm.devices, plan.default.devices):
        procs = [d.process_index for d in slice_]
        assert procs.count(0) == procs.count(1) == 2
    assert set(gangsm.devices).isdisjoint(plan.default.devices)


def test_carve_drops_unfittable_and_non_process_aligned_shapes():
    devs = [_Dev(p) for p in (0, 1)]
    # 3 is not a multiple of nproc=2, 8 exceeds the fleet: both dropped
    plan = sm.carve(devs, shapes=(8, 3, 2), nproc=2)
    assert [s.shape for s in plan.submeshes] == [2]
    assert plan.default is None  # nothing left over


def test_place_exact_then_elastic_replan_then_unplaceable():
    devs = [_Dev(0) for _ in range(6)]
    plan = sm.carve(devs, shapes=(4, 2), nproc=1)
    exact, replanned = plan.place(36, 36, 4)
    assert exact.shape == 4 and replanned is False
    # the stamp names a shape the carve no longer has: largest still-
    # fitting sub-mesh, reported as a replan (journaled gang_replanned)
    shrunk = sm.carve(devs[:2], shapes=(2,), nproc=1)
    moved, replanned = shrunk.place(36, 36, 4)
    assert moved.shape == 2 and replanned is True
    nowhere, replanned = shrunk.place(35, 35, 4)
    assert nowhere is None and replanned is False


# -- gang leases: fate-shared formation / break / sweep -----------------------


def test_gang_formation_is_all_or_nothing(tmp_path):
    root = str(tmp_path / "leases")
    mgr = LeaseManager(root, "replica-a", ttl_s=5.0)
    intruder = LeaseManager(root, "intruder", ttl_s=5.0)
    held = intruder.claim(gg.member_key(_KEY, 1))
    assert held is not None
    # member 1 is taken: the whole formation rolls back — no group lease,
    # no member-0 lease left holding capacity
    assert gg.GangLease.form(mgr, _KEY, 2) is None
    holders = mgr.holders()
    assert bucket_tag(gg.gang_key(_KEY)) not in holders
    assert bucket_tag(gg.member_key(_KEY, 0)) not in holders
    held.release()
    g = gg.GangLease.form(mgr, _KEY, 2)
    assert g is not None and len(g.members) == 2
    # the rolled-back claims escrowed their tokens: generation advanced
    assert g.generation >= 2
    g.release()


def test_gang_break_vs_member_renew_race_one_winner_tokens_monotonic(tmp_path):
    """The satellite race: a survivor breaks the gang while a member is
    mid-renew.  Exactly one side wins (the group-lease rename is the
    linearization point), the loser fences typed, and after re-formation
    every token — group generation and each member's — is strictly newer
    than anything the dead gang ever held."""
    root = str(tmp_path / "leases")
    holder = LeaseManager(root, "holder", ttl_s=0.1)
    survivor = LeaseManager(root, "survivor", ttl_s=0.1)
    peer = LeaseManager(root, "peer", ttl_s=0.1)
    g1 = gg.GangLease.form(holder, _KEY, 2)
    assert g1 is not None
    gen1 = g1.generation
    member_tokens1 = [m.token for m in g1.members]
    g1.renew_member(0)  # pre-race: renew under the gang's authority works

    broken = gg.break_gang(survivor, _KEY, 2)
    assert broken is not None and broken["owner"] == "holder"
    # exactly one break winner: the racing peer loses cleanly
    assert gg.break_gang(peer, _KEY, 2) is None
    # the holder's in-flight member renew fences instead of writing
    with pytest.raises(LeaseLost):
        g1.renew_member(0)
    with pytest.raises(LeaseLost):
        g1.renew()
    with pytest.raises(LeaseLost):
        g1.guard()

    g2 = gg.GangLease.form(survivor, _KEY, 2)
    assert g2 is not None
    assert g2.generation > gen1
    for new, old in zip((m.token for m in g2.members), member_tokens1):
        assert new > old  # member escrows advanced through the break
    g2.release()


def test_stale_gang_sweep_breaks_group_and_members_not_buckets(tmp_path):
    root = str(tmp_path / "leases")
    holder = LeaseManager(root, "holder", ttl_s=0.08)
    survivor = LeaseManager(root, "survivor", ttl_s=0.08)
    g = gg.GangLease.form(holder, _KEY, 2)
    assert g is not None
    plain = holder.claim(("bucket",) + _KEY)  # ordinary bucket lease
    assert plain is not None
    assert gg.stale_gangs(survivor) == []  # first pass opens the window
    time.sleep(0.12)  # the gang stops heartbeating
    (rec,) = gg.stale_gangs(survivor)
    assert rec["owner"] == "holder"
    holders = survivor.holders()
    # fate-shared: group AND every member lease are gone together...
    assert bucket_tag(gg.gang_key(_KEY)) not in holders
    for i in range(2):
        assert bucket_tag(gg.member_key(_KEY, i)) not in holders
    # ...but the ordinary bucket lease is not the gang sweep's business
    assert bucket_tag(("bucket",) + _KEY) in holders


# -- partial-gang heartbeats --------------------------------------------------


def test_replica_status_aggregates_partial_gang_heartbeats(tmp_path):
    """When only SOME gang members still heartbeat, the aggregation shows
    the sick gang instead of silently forgetting the dead member: the
    fresh member reports its gang, the missing one surfaces stale."""
    run_dir = str(tmp_path / "fleet")
    write_replica_heartbeat(
        run_dir, "gang0-m0", {"gang": 0, "member": 0, "slots": [1, 2]}
    )
    write_replica_heartbeat(
        run_dir, "gang0-m1", {"gang": 0, "member": 1, "slots": [1, 2]}
    )
    # member 1's writer died: its file stops being rewritten
    old = time.time() - 60.0
    os.utime(os.path.join(run_dir, "replicas", "gang0-m1.json"), (old, old))
    status = read_replica_status(run_dir, ttl_s=5.0)
    by_id = {r["replica"]: r for r in status}
    assert by_id["gang0-m0"]["stale"] is False
    assert by_id["gang0-m0"]["gang"] == 0
    assert by_id["gang0-m1"]["stale"] is True  # visible, not forgotten
    fresh = [r for r in status if not r["stale"]]
    assert len(fresh) == 1  # the gang is NOT quorate: 1 of 2 members


# -- sub-mesh admission (typed rejects at the door) ---------------------------


def _req(nx, ny):
    return SimRequest(
        ra=1e4, pr=1.0, nx=nx, ny=ny, dt=0.01, horizon=0.1, bc="rbc"
    )


def test_admit_submesh_stamps_rejects_and_passes_through():
    cfg = SubmeshConfig(shapes=(2,), shard_min_nx=34, max_pending=2)
    # feature off: byte-identical pass-through
    small = _req(17, 17)
    assert qos.admit_submesh(small, 0, None) is small
    # vmapped traffic below the threshold: unstamped
    assert qos.admit_submesh(small, 0, cfg).submesh == 0
    # sharded traffic: stamped with the canonical shape
    stamped = qos.admit_submesh(_req(34, 34), 0, cfg)
    assert stamped.submesh == 2
    assert len(stamped.compat_key) == 11 and stamped.compat_key[10] == 2
    # permanent mismatch: typed 400 at POST, not a durable poison pill
    with pytest.raises(RequestError) as exc:
        qos.admit_submesh(_req(35, 35), 0, cfg)
    assert exc.value.reason == "no_submesh"
    # transient sharded backlog: 429 with queue-depth-derived Retry-After
    with pytest.raises(AdmissionError) as exc:
        qos.admit_submesh(_req(34, 34), 2, cfg)
    assert exc.value.reason == "capacity"
    assert exc.value.retry_after_s >= 2.0


# -- gang fault scoping -------------------------------------------------------


def test_fault_plan_gang_scope_parsing_and_binding():
    plan = FaultPlan.from_spec("kill@5:gang0member1")
    assert (plan.kind, plan.step) == ("kill", 5)
    assert (plan.gang, plan.member) == (0, 1)
    assert plan.scoped_here() is False  # no gang campaign bound
    plan.bind_gang(0, 1)
    assert plan.scoped_here() is True
    plan.bind_gang(0, 0)  # right gang, wrong member
    assert plan.scoped_here() is False
    plan.bind_gang(None, None)  # campaign closed: never acts again
    assert plan.scoped_here() is False
    # gang-wide scope (no member): every bound member of gang 2 acts
    wide = FaultPlan.from_spec("nan@3:gang2")
    wide.bind_gang(2, 1)
    assert wide.scoped_here() is True


@pytest.mark.parametrize(
    "spec",
    [
        "kill@5:gang",  # missing index
        "kill@5:gangXmember1",  # non-numeric gang
        "kill@5:gang0member",  # member keyword without index
        "kill@5:gang0memberX",  # non-numeric member
        "kill@5:gang0extra",  # trailing junk
    ],
)
def test_fault_plan_gang_scope_malformed_raise_typed(spec):
    with pytest.raises(FaultSpecError):
        FaultPlan.from_spec(spec)


# -- default config: byte-identical to today ----------------------------------


def test_default_config_serves_without_any_gang_rows(tmp_path):
    """The acceptance guard: ``ServeConfig.submesh=None`` (the default)
    must be byte-identical to the pre-gang service — bare 10-tuple
    bucket keys, zero gang/submesh journal rows, no gang counters."""
    cfg = ServeConfig(
        run_dir=str(tmp_path / "serve"),
        slots=2,
        chunk_steps=4,
        checkpoint_every_s=None,
        http_port=None,
    )
    assert cfg.submesh is None
    srv = SimServer(cfg)
    req = srv.submit(
        dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.05, bc="rbc")
    )
    assert len(req.compat_key) == 10  # bare key: no stamp element
    summary = srv.serve()
    assert summary["completed"] == 1 and summary["failed"] == 0
    events = read_journal(os.path.join(cfg.run_dir, "journal.jsonl"))
    gangish = [
        e["event"]
        for e in events
        if e["event"].startswith(("gang_", "submesh_"))
    ]
    assert gangish == []
    assert "gangs" not in srv.stats()
