"""Telemetry subsystem tests (rustpde_mpi_tpu/telemetry/): the metrics
registry (counters/gauges/log-bucket histograms, snapshot/delta/merge), the
Prometheus text exposition, flight-recorder tracing + incident dumps, the
ThroughputMonitor SLO loop, and the hard contract — instrumented runs are
BIT-identical to telemetry-off runs.

Runner/serve integration reuses the 17^2 shapes every other harness test
compiles; the live mid-soak ``/metrics`` scrape rides test_serve.py's HTTP
tests (same daemon-server machinery)."""

import json
import math
import os

import numpy as np
import pytest

import jax

from rustpde_mpi_tpu import (
    DivergenceError,
    Navier2D,
    ResilientRunner,
    telemetry,
)
from rustpde_mpi_tpu.telemetry import (
    FlightRecorder,
    MetricsDumper,
    MetricsRegistry,
    ThroughputMonitor,
    prometheus_text,
)
from rustpde_mpi_tpu.telemetry import metrics as tmetrics
from rustpde_mpi_tpu.telemetry import tracing as ttracing

h5py = pytest.importorskip("h5py")


def _model(seed=0):
    m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    m.init_random(0.1, seed=seed)
    return m


# -- registry ------------------------------------------------------------------


def test_counters_gauges_and_labels():
    reg = MetricsRegistry()
    c = reg.counter("requests_total", "help text", result="done")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    # same (name, labels) -> same handle; different labels -> distinct series
    assert reg.counter("requests_total", result="done") is c
    other = reg.counter("requests_total", result="failed")
    assert other is not c and other.value == 0.0
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(7)
    g.inc(3)
    g.dec(1)
    assert g.value == 9
    # a name cannot change kind
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("requests_total")


def test_histogram_log_buckets_and_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("latency_seconds")
    values = [0.001, 0.01, 0.05, 0.1, 0.1, 0.2, 1.0, 5.0, 0.0]
    for v in values:
        h.observe(v)
    assert h.count == len(values)
    assert h.sum == pytest.approx(sum(values))
    assert h.min == 0.0 and h.max == 5.0
    # log-bucketed: the quantile is bucket-accurate (ratio ~1.26), NOT exact
    assert h.quantile(0.5) == pytest.approx(0.1, rel=0.3)
    assert h.quantile(0.99) == pytest.approx(5.0, rel=0.3)
    assert h.quantile(0.0) == 0.0  # the zero bucket
    # cumulative buckets are monotone and end at the total count
    buckets = h.buckets()
    counts = [n for _, n in buckets]
    assert counts == sorted(counts) and counts[-1] == h.count
    edges = [le for le, _ in buckets]
    assert edges == sorted(edges)
    # no sample retention: storage is bucket counts, not the observations
    d = h.to_dict()
    assert d["count"] == len(values) and "p99" in d
    assert len(d["counts"]) < len(values)
    # a non-finite observation is COUNTED but must not poison sum/min/max
    # (a single NaN would otherwise NaN every rate()/avg query forever)
    h.observe(float("nan"))
    h.observe(float("inf"))
    assert h.count == len(values) + 2
    assert math.isfinite(h.sum) and h.max == 5.0
    assert math.isfinite(h.quantile(0.9))


def test_snapshot_delta_and_multihost_merge():
    reg = MetricsRegistry()
    reg.counter("steps_total").inc(10)
    reg.gauge("dt").set(0.01)
    reg.histogram("write_seconds").observe(0.5)
    snap = reg.snapshot()
    assert snap["steps_total"]["kind"] == "counter"
    json.dumps(snap)  # plain-JSON contract
    reg.counter("steps_total").inc(5)
    reg.histogram("write_seconds").observe(0.5)
    delta = reg.delta(snap)
    assert delta["steps_total"]["series"][0]["value"] == 5.0
    assert delta["write_seconds"]["series"][0]["count"] == 1
    # merge: counters/histograms sum, gauges keep per-host labeled values
    merged = tmetrics.merge_snapshots([reg.snapshot(), snap])
    assert merged["steps_total"]["series"][0]["value"] == 25.0
    assert merged["write_seconds"]["series"][0]["count"] == 3
    hosts = {s["labels"].get("host") for s in merged["dt"]["series"]}
    assert hosts == {"0", "1"}
    # single process: the gathered view IS the local snapshot
    assert tmetrics.gather_global_snapshot(reg) == reg.snapshot()


def test_prometheus_exposition_format():
    from test_serve import _parse_prometheus

    reg = MetricsRegistry()
    reg.counter("a_total", "things", kind="x\"y\\z").inc(2)
    reg.gauge("b").set(1.5)
    h = reg.histogram("c_seconds", "hist help")
    for v in (0.1, 0.2, 3.0):
        h.observe(v)
    text = prometheus_text(reg)
    samples = _parse_prometheus(text)  # asserts every line parses
    assert samples["b"][""] == (1.5,)
    assert "# TYPE c_seconds histogram" in text
    assert "# HELP c_seconds hist help" in text
    # cumulative le series with +Inf == _count
    inf = [k for k in samples["c_seconds_bucket"] if '+Inf' in k]
    assert inf and samples["c_seconds_bucket"][inf[0]] == (3.0,)
    assert samples["c_seconds_count"][""] == (3.0,)
    assert samples["c_seconds_sum"][""][0] == pytest.approx(3.3)
    # label escaping survives the round trip
    assert '\\"' in text and "\\\\" in text


def test_disabled_registry_is_noop_and_cheap():
    prev = tmetrics.enabled()
    try:
        telemetry.set_enabled(False)
        c = telemetry.counter("nope_total")
        c.inc(100)
        assert c.value == 0.0
        # the shared null span: no allocation per call
        assert ttracing.span("a") is ttracing.span("b")
        assert telemetry.dump_flight_record("/nonexistent", "x") is None
    finally:
        telemetry.set_enabled(prev)


# -- tracing -------------------------------------------------------------------


def test_flight_recorder_spans_ring_and_dump(tmp_path):
    rec = FlightRecorder(capacity=32)
    t0 = rec.now_us()
    rec.add_complete("dispatch", t0, 125.0, {"steps": 4})
    rec.add_instant("fault", {"kind": "nan"})
    for i in range(100):  # the ring stays bounded
        rec.add_complete("spam", rec.now_us(), 1.0)
    events = rec.events()
    assert len(events) == 32
    path = rec.dump(str(tmp_path / "flight.json"), reason="test")
    data = json.load(open(path))
    assert isinstance(data["traceEvents"], list) and data["traceEvents"]
    ev = data["traceEvents"][-1]
    # the Perfetto/Chrome trace-event contract
    assert ev["ph"] == "X" and {"name", "ts", "dur", "pid", "tid"} <= set(ev)
    assert data["otherData"]["reason"] == "test"
    assert rec.dumped == 1


def test_span_records_and_annotates_errors():
    before = len(ttracing.RECORDER.events())
    with telemetry.span("outer", step=3):
        pass
    with pytest.raises(RuntimeError):
        with telemetry.span("failing"):
            raise RuntimeError("boom")
    events = ttracing.RECORDER.events()
    assert len(events) >= before + 2
    named = {e["name"]: e for e in events[-4:]}
    assert named["outer"]["args"] == {"step": 3}
    assert named["failing"]["args"]["error"] == "RuntimeError"


def test_metrics_dumper_cadence_and_reader(tmp_path):
    reg = MetricsRegistry()
    reg.counter("x_total").inc(3)
    path = str(tmp_path / "metrics.jsonl")
    d = MetricsDumper(path, every_s=1e9, registry=reg)
    assert d.maybe_dump() is False  # first call only arms the clock
    assert d.maybe_dump() is False  # cadence not elapsed
    assert d.dump(step=7) is True  # force
    reg.counter("x_total").inc(2)
    assert d.dump(step=9) is True
    records = telemetry.read_metrics_jsonl(path)
    assert len(records) == 2
    assert records[0]["step"] == 7
    assert records[1]["delta"]["x_total"]["series"][0]["value"] == 2.0
    # torn tail tolerated
    with open(path, "a") as fh:
        fh.write('{"torn')
    assert len(telemetry.read_metrics_jsonl(path)) == 2


# -- the SLO monitor -----------------------------------------------------------


def test_throughput_monitor_detects_regression():
    clock = iter([0.0, 1.0, 2.0, 3.0, 4.0, 14.0, 15.0]).__next__
    mon = ThroughputMonitor(
        window=4, warmup=2, tolerance=0.5, min_interval_s=0.0, clock=clock
    )
    verdicts = [mon.record(100) for _ in range(6)]
    assert all(v is None for v in verdicts[:5])
    hit = verdicts[5]  # elapsed 10s instead of 1s -> 10x regression
    assert hit is not None
    assert hit["ratio"] == pytest.approx(0.1)
    assert hit["baseline_steps_per_sec"] == pytest.approx(100.0)
    assert mon.events == 1
    # recovery at the old rate reports nothing further
    assert mon.record(100) is None


def test_throughput_monitor_rate_limited():
    # a SUSTAINED regression journals a heartbeat, not a line per chunk
    ticks = iter([0, 1, 2, 3, 4, 14, 24, 34]).__next__
    mon = ThroughputMonitor(
        window=8, warmup=2, tolerance=0.5, min_interval_s=100.0, clock=ticks
    )
    verdicts = [mon.record(10) for _ in range(8)]
    assert sum(1 for v in verdicts if v) == 1


# -- runner integration --------------------------------------------------------


def test_instrumented_run_bit_identical_to_telemetry_off(tmp_path):
    """THE hard constraint, CI-asserted: telemetry must never touch traced
    programs — the full runner path with metrics+tracing ON produces a
    final state byte-identical to the same run with telemetry OFF."""
    states = {}
    prev = tmetrics.enabled()
    try:
        for key, on in (("on", True), ("off", False)):
            telemetry.set_enabled(on)
            m = _model(seed=3)
            runner = ResilientRunner(
                m,
                max_time=0.12,
                run_dir=str(tmp_path / key),
                checkpoint_every_s=None,
                max_chunk_steps=4,
            )
            summary = runner.run()
            assert summary["outcome"] == "done"
            states[key] = jax.device_get(m.state)
    finally:
        telemetry.set_enabled(prev)
    for a, b in zip(states["on"], states["off"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the ON run left live telemetry behind; the OFF run left none
    assert os.path.exists(tmp_path / "on" / "metrics.jsonl")
    assert not os.path.exists(tmp_path / "off" / "metrics.jsonl")
    recs = telemetry.read_metrics_jsonl(str(tmp_path / "on" / "metrics.jsonl"))
    steps = recs[-1]["snapshot"]["runner_steps_total"]["series"][0]["value"]
    assert steps >= 12  # this run's steps rode the shared counter


def test_flight_record_dumped_on_divergence(tmp_path):
    m = _model(seed=1)
    runner = ResilientRunner(
        m,
        max_time=0.5,
        run_dir=str(tmp_path / "run"),
        checkpoint_every_s=None,
        max_retries=0,
        fault="nan@4",
        max_chunk_steps=4,
    )
    with pytest.raises(DivergenceError):
        runner.run()
    dumps = [f for f in os.listdir(tmp_path / "run") if f.startswith("flight_")]
    assert dumps, "no flight record dumped on DivergenceError"
    data = json.load(open(tmp_path / "run" / dumps[0]))
    names = {e["name"] for e in data["traceEvents"]}
    assert "dispatch" in names and "fault_injected" in names
    # the journal points at the incident file
    from rustpde_mpi_tpu.utils.journal import read_journal

    events = read_journal(str(tmp_path / "run" / "journal.jsonl"))
    fr = [e for e in events if e.get("event") == "flight_record"]
    assert fr and fr[0]["reason"] == "DivergenceError"


def test_flight_record_dumped_on_sigterm_preempt(tmp_path):
    m = _model(seed=2)
    runner = ResilientRunner(
        m,
        max_time=1.0,
        run_dir=str(tmp_path / "run"),
        checkpoint_every_s=None,
        fault="kill@6",  # a real SIGTERM to our own pid, mid-run
        max_chunk_steps=4,
    )
    summary = runner.run()
    assert summary["outcome"] == "preempted"
    dumps = [f for f in os.listdir(tmp_path / "run") if f.startswith("flight_preempt")]
    assert dumps, "no flight record dumped on the SIGTERM drain"


def test_perf_degraded_journaled_by_runner(tmp_path):
    """The SLO loop end-to-end: a fake-clock monitor sees the boundary rate
    collapse and the runner journals the typed perf_degraded event."""
    m = _model(seed=4)
    runner = ResilientRunner(
        m,
        max_time=0.1,
        save_intervall=0.01,  # one SLO sample per boundary
        run_dir=str(tmp_path / "run"),
        checkpoint_every_s=None,
    )
    seq = iter([0.0, 1.0, 2.0, 3.0, 103.0, 104.0, 105.0, 106.0, 107.0, 108.0])

    def clock():
        try:
            return next(seq)
        except StopIteration:
            return 1000.0

    runner.slo = ThroughputMonitor(
        window=4, warmup=2, tolerance=0.5, min_interval_s=0.0, clock=clock
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    from rustpde_mpi_tpu.utils.journal import read_journal

    events = read_journal(str(tmp_path / "run" / "journal.jsonl"))
    degraded = [e for e in events if e.get("event") == "perf_degraded"]
    assert degraded, [e.get("event") for e in events]
    assert degraded[0]["ratio"] < 0.5
    assert math.isfinite(degraded[0]["steps_per_sec"])


def test_flight_record_dumped_on_dispatch_hang(tmp_path):
    from rustpde_mpi_tpu import DispatchHang

    m = _model(seed=5)
    runner = ResilientRunner(
        m,
        max_time=0.5,
        run_dir=str(tmp_path / "run"),
        checkpoint_every_s=None,
        fault="slow@4",
        dispatch_timeout_s=0.3,
        max_chunk_steps=4,
    )
    with pytest.raises(DispatchHang):
        runner.run()
    dumps = [
        f for f in os.listdir(tmp_path / "run") if f.startswith("flight_dispatch_hang")
    ]
    assert dumps, "no flight record dumped on DispatchHang"


# -- request tracing (telemetry/reqtrace.py) -----------------------------------


def test_reqtrace_mint_and_log_roundtrip(tmp_path):
    from rustpde_mpi_tpu.telemetry import reqtrace

    ctx = reqtrace.mint()
    assert len(ctx["trace_id"]) == 16 and len(ctx["span"]) == 8
    assert reqtrace.mint()["trace_id"] != ctx["trace_id"]

    log = reqtrace.RequestTraceLog(capacity=64)
    log.record(ctx["trace_id"], "chunk", 100.0, 0.5, {"steps": 4})
    log.record(ctx["trace_id"], "marker", 101.0)
    ev = log.events()
    assert ev[0]["ph"] == "X" and ev[0]["dur"] == 0.5e6
    assert ev[0]["args"] == {"trace_id": ctx["trace_id"], "steps": 4}
    assert ev[1]["ph"] == "i"
    # bounded: past capacity events are counted dropped, not grown
    small = reqtrace.RequestTraceLog(capacity=64)
    for i in range(200):
        small.record("t", "spam", float(i))
    assert len(small.events()) == 64 and small.dropped == 136
    # drain empties
    assert len(log.drain()) == 2 and log.events() == []


def test_reqtrace_binding_annotates_spans_and_flight_dumps(tmp_path):
    from rustpde_mpi_tpu.telemetry import reqtrace
    from rustpde_mpi_tpu.telemetry import tracing as ttr

    try:
        reqtrace.bind_slots({0: "aaaa", 1: "bbbb", 2: "aaaa"})
        assert reqtrace.active_ids() == ["aaaa", "bbbb"]
        with telemetry.span("bound_span", step=1):
            pass
        ev = ttr.RECORDER.events()[-1]
        assert ev["args"]["trace_ids"] == ["aaaa", "bbbb"]
        assert ev["args"]["step"] == 1
        # sequenced, attributed flight dumps: monotonic _nNNNN filenames,
        # seq + trace_ids in the payload (the chaos-soak pile stays sorted
        # and attributable)
        p1 = telemetry.dump_flight_record(str(tmp_path), "probe")
        p2 = telemetry.dump_flight_record(str(tmp_path), "probe")
        assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)
        d1 = json.load(open(p1))
        d2 = json.load(open(p2))
        assert d2["otherData"]["seq"] == d1["otherData"]["seq"] + 1
        assert d1["otherData"]["trace_ids"] == ["aaaa", "bbbb"]
        assert f"_n{d1['otherData']['seq']:04d}" in os.path.basename(p1)
    finally:
        reqtrace.clear_active()
    # cleared: spans go back to unannotated
    with telemetry.span("unbound_span"):
        pass
    assert "trace_ids" not in (ttr.RECORDER.events()[-1].get("args") or {})


def test_reqtrace_campaign_write_and_assembly(tmp_path):
    """Single-process end-to-end of the durable pieces: chunk events land
    in a per-campaign Perfetto file; assembly reconstructs one timeline
    from journal rows + the campaign file, keyed by the trace_id."""
    from rustpde_mpi_tpu.telemetry import reqtrace
    from rustpde_mpi_tpu.utils.journal import JournalWriter

    run_dir = str(tmp_path / "serve")
    cdir = os.path.join(run_dir, "campaigns", "deadbeef0000")
    os.makedirs(cdir)
    tid = "feedfacefeedface"
    reqtrace.chunk_span(tid, 1000.0, 0.25, slot=0, steps=4)
    path = reqtrace.write_campaign_trace(cdir, "deadbeef0000")
    assert path and os.path.basename(path) == "trace_0000.json"
    # a second campaign close APPENDS a new file (incarnations never clobber)
    reqtrace.chunk_span(tid, 1001.0, 0.25, slot=0, steps=4)
    path2 = reqtrace.write_campaign_trace(cdir, "deadbeef0000")
    assert os.path.basename(path2) == "trace_0001.json"
    # no events -> no file, no error
    assert reqtrace.write_campaign_trace(cdir, "deadbeef0000") is None

    w = JournalWriter(os.path.join(run_dir, "journal.jsonl"))
    w.append({"event": "server_start"})
    w.append({"event": "request_admitted", "id": "r1", "trace_id": tid})
    w.append({"event": "request_scheduled", "id": "r1", "trace_id": tid})
    w.append({"event": "request_done", "id": "r1", "trace_id": tid})
    w.close()
    trace = reqtrace.assemble_request_trace(run_dir, "r1")
    assert trace["otherData"]["trace_id"] == tid
    assert trace["otherData"]["incarnations"] == 1
    names = [e["name"] for e in trace["traceEvents"]]
    assert "request_admitted" in names and "chunk" in names
    assert "queued" in names and "running" in names  # derived phases
    assert all(e["args"]["trace_id"] == tid for e in trace["traceEvents"])
    ts = [e["ts"] for e in trace["traceEvents"]]
    assert ts == sorted(ts) and ts[0] == 0.0
    # unknown request: None, not an empty payload
    assert reqtrace.assemble_request_trace(run_dir, "nope") is None


def test_journal_rows_carry_absolute_time(tmp_path):
    from rustpde_mpi_tpu.utils.journal import JournalWriter, read_journal
    import time as _t

    path = str(tmp_path / "j.jsonl")
    w = JournalWriter(path)
    before = _t.time()
    w.append({"event": "a"})
    w.append({"event": "b", "t": 123.0})  # caller-provided stamps win
    w.close()
    rows = read_journal(path)
    assert before - 1 <= rows[0]["t"] <= _t.time() + 1
    assert rows[1]["t"] == 123.0


# -- compile/device attribution (telemetry/compile_log.py) ---------------------


def test_compile_log_build_attribution_and_recompile_count():
    from rustpde_mpi_tpu.telemetry import compile_log

    key = ("dns", 17, 17, 1e4, 1.0, 0.123456, 1.0, "rbc", False, ())
    tag = compile_log.key_tag(key)
    assert len(tag) == 12
    first = compile_log.observe_build(key, 0.5, kind="dns")
    assert first["recompile"] is False and first["builds"] >= 1
    assert first["phase"] == "build"
    again = compile_log.observe_build(key, 0.25, kind="dns")
    assert again["recompile"] is True and again["builds"] == first["builds"] + 1
    snap = telemetry.snapshot()
    series = {
        tuple(sorted(s["labels"].items())): s
        for s in snap["compile_build_seconds"]["series"]
    }
    assert (("key", tag), ("phase", "build")) in series
    assert series[(("key", tag), ("phase", "build"))]["count"] >= 2
    # a non-build phase rides its own series and does NOT bump the per-key
    # build count (TTFC attribution sums across phases instead of ~2x)
    entry = compile_log.observe_build(key, 0.1, kind="dns", phase="entry_points")
    assert entry["phase"] == "entry_points" and entry["recompile"] is False
    assert compile_log.build_counts()[tag] == again["builds"]
    assert compile_log.last_build_wall(key) == 0.25
    recomp = {
        s["labels"]["key"]: s["value"]
        for s in snap["compile_recompiles_total"]["series"]
    }
    assert recomp[tag] >= 1
    assert compile_log.build_counts()[tag] >= 2
    # time-to-first-chunk rides the same label
    compile_log.observe_first_chunk(key, 1.5)
    ttfc = telemetry.snapshot()["serve_time_to_first_chunk_seconds"]
    assert any(s["labels"]["key"] == tag for s in ttfc["series"])


def test_device_memory_gauges_none_safe():
    """CPU backends report no memory stats: the helper returns the
    None-marked dict and the gauge update counts zero devices instead of
    inventing zeros."""
    from rustpde_mpi_tpu.telemetry import compile_log
    from rustpde_mpi_tpu.utils.profiling import device_memory_stats

    stats = device_memory_stats()
    assert stats  # at least one local device
    reported = compile_log.update_device_memory_gauges()
    with_stats = sum(1 for v in stats.values() if v)
    assert reported == with_stats


def test_profiler_capture_single_flight_and_bounds(tmp_path):
    from rustpde_mpi_tpu.telemetry.compile_log import ProfilerCapture

    started, stopped = [], []
    cap = ProfilerCapture(
        start_fn=lambda d: started.append(d), stop_fn=lambda: stopped.append(1)
    )
    assert cap.start(str(tmp_path), "nope")["started"] is False
    assert cap.start(str(tmp_path), -1)["started"] is False
    status = cap.start(str(tmp_path / "p"), 0.4, reason="test")
    assert status["started"] is True and status["seconds"] == 0.4
    # single-flight: a second start while the window runs is refused
    refused = cap.start(str(tmp_path / "p2"), 0.1)
    assert refused["started"] is False and "already running" in refused["error"]
    for _ in range(200):
        if not cap.busy:
            break
        import time as _t

        _t.sleep(0.01)
    assert not cap.busy and cap.captures == 1
    assert started == [str(tmp_path / "p")] and stopped == [1]
    assert cap.last.get("done") is True
    # the cap clamps absurd windows
    import rustpde_mpi_tpu.config  # noqa: F401 — registry import for env_get

    assert cap.start(str(tmp_path / "p3"), 1e9)["seconds"] <= cap.max_seconds()


def test_perf_degraded_auto_capture_one_shot(tmp_path, monkeypatch):
    from rustpde_mpi_tpu.telemetry import compile_log

    cap = compile_log.ProfilerCapture(
        start_fn=lambda d: None, stop_fn=lambda: None
    )
    monkeypatch.setattr(compile_log, "CAPTURE", cap)
    monkeypatch.setattr(compile_log, "_degrade_fired", False)
    first = compile_log.capture_on_perf_degraded(str(tmp_path))
    assert first is not None and first["reason"] == "perf_degraded"
    # one-shot per process: a second regression only counts
    assert compile_log.capture_on_perf_degraded(str(tmp_path)) is None


def test_metrics_dumper_single_process_path_unchanged(tmp_path):
    """The multihost collision fix suffixes NON-root ranks only; on a
    single process (and on root) the path — and every existing reader —
    is untouched.  The 2-proc suffix assertion lives in mp_worker's
    serve_campaign mode."""
    path = str(tmp_path / "metrics.jsonl")
    d = MetricsDumper(path, every_s=1e9)
    assert d.path == path
