"""End-to-end integrity tests (rustpde_mpi_tpu/integrity/ + the runner,
checkpoint, queue, and fleet wiring): on-device state digests (determinism,
single-bit sensitivity, per-member localization), the shadow re-execution
audit catching an injected silent bitflip and rolling back to a
bit-identical trajectory, the quarantine ledger's strike/expiry
bookkeeping, digest-verified sharded checkpoints, disk-full containment
(ENOSPC -> storage_full 503 at admission, in-memory-rollback-only
degradation on the checkpoint writer), idempotency-key dedupe, clock-jump
hardening, and the fleet proxy's cross-replica digest voting.

The 2-process ``bitflip@<n>:host1`` soak (host quarantined, zero requests
lost) rides tests/mp_worker.py mode ``integrity_serve`` in the slow tier.
"""

import dataclasses
import errno
import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from model_builders import build_rbc17
from rustpde_mpi_tpu.config import IntegrityConfig, IOConfig, ServeConfig
from rustpde_mpi_tpu.integrity import (
    IntegrityError,
    QuarantineLedger,
    flip_state_bit,
)
from rustpde_mpi_tpu.serve import AdmissionError, DurableQueue, SimRequest, SimServer
from rustpde_mpi_tpu.utils import checkpoint as cp
from rustpde_mpi_tpu.utils.journal import read_journal
from rustpde_mpi_tpu.utils.resilience import ResilientRunner

_FIELDS = ("temp", "velx", "vely", "pres")


def _events(run_dir):
    return [e for e in read_journal(os.path.join(run_dir, "journal.jsonl"),
                                    on_error="skip")]


def _armed17(cadence=1):
    model = build_rbc17()
    model.set_integrity(IntegrityConfig(cadence=cadence))
    return model


def _digest(model):
    return np.asarray(model.state_digest_async().result())


# -- digests ------------------------------------------------------------------


def test_digest_deterministic_and_single_bit_sensitive():
    model = _armed17()
    d0 = _digest(model)
    assert d0.dtype == np.uint32
    assert np.array_equal(d0, _digest(model))  # pure consumer, no drift
    # one mantissa-bit flip is visible; flipping the same bit back restores
    clean = model.state
    model.state, info = flip_state_bit(model.state, step=7)
    model._obs_cache = None
    d1 = _digest(model)
    assert not np.array_equal(d0, d1), info
    model.state, _ = flip_state_bit(model.state, step=7)
    model._obs_cache = None
    assert np.array_equal(d0, _digest(model))
    model.state = clean


def test_ensemble_member_digests_localize_the_flip():
    from rustpde_mpi_tpu import NavierEnsemble

    ens = NavierEnsemble.from_seeds(build_rbc17(), seeds=range(3))
    ens.set_integrity(IntegrityConfig())
    d0 = _digest(ens)
    assert d0.shape == (3,)
    ens.state, info = flip_state_bit(ens.state, step=4, member=1)
    ens._obs_cache = None
    d1 = _digest(ens)
    assert info["member"] == 1
    changed = [int(i) for i in np.flatnonzero(d0 != d1)]
    assert changed == [1]


# -- runner: detection, rollback, bit-equality --------------------------------


def _run17(tmp_path, name, *, integrity, fault=None):
    model = build_rbc17()
    if integrity:
        model.set_integrity(IntegrityConfig(cadence=1))
    runner = ResilientRunner(
        model,
        max_time=0.4,
        run_dir=str(tmp_path / name),
        checkpoint_every_s=None,
        max_chunk_steps=8,
        fault=fault,
        io=IOConfig(async_checkpoints=False, overlap_dispatch=False),
    )
    summary = runner.run()
    return model, summary


def test_bitflip_caught_rolled_back_and_bit_equal_to_clean(tmp_path):
    """The tentpole acceptance path: an injected silent flip is detected
    by the shadow audit, contained by an in-memory rollback to the last
    verified state, and the completed run's final state is BIT-EQUAL to
    an uninjected run's — and arming digests does not perturb the
    trajectory (clean armed == clean disarmed)."""
    clean_off, _ = _run17(tmp_path, "clean_off", integrity=False)
    clean_on, _ = _run17(tmp_path, "clean_on", integrity=True)
    hit, summary = _run17(tmp_path, "flip", integrity=True, fault="bitflip@16")
    assert summary["outcome"] == "done"
    for name in _FIELDS:
        a = np.asarray(getattr(clean_off.state, name))
        b = np.asarray(getattr(clean_on.state, name))
        c = np.asarray(getattr(hit.state, name))
        np.testing.assert_array_equal(a, b, err_msg=f"armed-vs-off {name}")
        np.testing.assert_array_equal(a, c, err_msg=f"injected {name}")
    names = [e.get("event") for e in _events(tmp_path / "flip")]
    assert "bitflip_injected" in names
    assert "integrity_mismatch" in names
    assert "integrity_rollback" in names
    # audits resume (and pass) after the rollback
    assert names.index("integrity_rollback") < len(names) - 1
    ok_audits = [e for e in _events(tmp_path / "flip")
                 if e.get("event") == "integrity_audit"
                 and e.get("result") == "ok"]
    assert ok_audits
    # the clean run never fired a mismatch
    clean_names = [e.get("event") for e in _events(tmp_path / "clean_on")]
    assert "integrity_mismatch" not in clean_names


def test_bitflip_without_integrity_is_silent_wrong_but_finite(tmp_path):
    """Integrity OFF control: the same injection completes with no
    detection — a wrong-but-finite answer, which is exactly the failure
    mode the digests exist to close."""
    clean, _ = _run17(tmp_path, "ctl_clean", integrity=False)
    hit, summary = _run17(tmp_path, "ctl_flip", integrity=False,
                          fault="bitflip@16")
    assert summary["outcome"] == "done"
    names = [e.get("event") for e in _events(tmp_path / "ctl_flip")]
    assert "bitflip_injected" in names
    assert "integrity_mismatch" not in names
    diff = False
    for name in _FIELDS:
        a = np.asarray(getattr(clean.state, name))
        b = np.asarray(getattr(hit.state, name))
        assert np.isfinite(b).all(), name
        diff = diff or not np.array_equal(a, b)
    assert diff  # wrong: the corruption propagated into the answer


# -- quarantine ledger --------------------------------------------------------


def test_quarantine_ledger_strikes_expiry_and_persistence(tmp_path):
    now = [1000.0]
    led = QuarantineLedger(str(tmp_path), strikes=2, strike_ttl_s=60.0,
                           clock=lambda: now[0])
    assert led.strike("cpu:0@proc0", step=5, detail="shadow") is False
    assert led.strikes_for("cpu:0@proc0") == 1
    assert led.quarantined() == ()
    # a second strike within the TTL quarantines, exactly once
    assert led.strike("cpu:0@proc0", step=9, detail="chain") is True
    assert led.strike("cpu:0@proc0", step=11) is False  # already quarantined
    assert led.quarantined() == ("cpu:0@proc0",)
    # strikes EXPIRE: a transient upset decays instead of accumulating
    assert led.strike("cpu:1@proc0", step=2) is False
    now[0] += 120.0
    assert led.strikes_for("cpu:1@proc0") == 0
    assert led.strike("cpu:1@proc0", step=3) is False  # count restarted
    # quarantine does NOT expire, and the file round-trips a fresh reader
    led2 = QuarantineLedger(str(tmp_path), strikes=2, clock=lambda: now[0])
    assert led2.is_quarantined("cpu:0@proc0")
    assert led2.quarantined() == ("cpu:0@proc0",)


# -- verified checkpoints -----------------------------------------------------


def test_sharded_checkpoint_carries_and_verifies_digest(tmp_path):
    model = _armed17()
    model.update_n(4)
    path = cp.checkpoint_path(str(tmp_path), 4)
    cp.write_sharded_snapshot(model, path, step=4)
    # the manifest's replicated root data carries the on-device digest
    assert "integrity_digest" in {k for k, *_ in model.snapshot_root_items()}
    # restore recomputes and compares: the device->disk->device loop closes
    target = _armed17()
    target.read(path)
    for name in _FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(model.state, name)),
            np.asarray(getattr(target.state, name)),
            err_msg=name,
        )
    # a manifest digest that does not match the restored state is a typed
    # rejection naming the checkpoint check
    with pytest.raises(IntegrityError) as exc:
        target._verify_restored_digest(np.uint32(0xDEAD))
    assert exc.value.check == "checkpoint"


# -- disk-full containment ----------------------------------------------------


def test_enospc_checkpoint_degrades_to_memory_rollback(tmp_path, monkeypatch):
    """ENOSPC on the async checkpoint writer journals
    ``checkpoint_failed{errno}`` and flips the run to
    in-memory-rollback-only: later checkpoints are skipped (journaled),
    the writer is unwedged, and the run still completes."""
    model = build_rbc17()
    run_dir = str(tmp_path / "run")

    def boom(snap, path):
        raise OSError(errno.ENOSPC, "No space left on device", path)

    monkeypatch.setattr(cp, "write_host_snapshot", boom)
    runner = ResilientRunner(
        model,
        max_time=0.04,
        run_dir=run_dir,
        checkpoint_every_s=0.0,
        max_chunk_steps=8,
        io=IOConfig(async_checkpoints=True, overlap_dispatch=False),
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    assert runner._ckpt_disabled
    rows = _events(tmp_path / "run")
    failed = [e for e in rows if e.get("event") == "checkpoint_failed"]
    assert any(e.get("errno") == errno.ENOSPC for e in failed)
    assert any(e.get("degraded") == "in_memory_rollback_only" for e in failed)
    assert any(e.get("event") == "checkpoint_skipped"
               and e.get("cause") == "storage_full" for e in rows)


def test_enospc_admission_is_typed_storage_full(tmp_path, monkeypatch):
    q = DurableQueue(str(tmp_path / "q"))

    def full(req):
        raise OSError(errno.ENOSPC, "No space left on device")

    monkeypatch.setattr(q, "_enqueue", full)
    with pytest.raises(AdmissionError) as exc:
        q.submit(SimRequest(ra=1e4, horizon=0.1))
    assert exc.value.reason == "storage_full"
    assert exc.value.retry_after_s > 0
    # any OTHER OSError still propagates raw — only disk-full is admission
    monkeypatch.setattr(
        q, "_enqueue",
        lambda req: (_ for _ in ()).throw(OSError(errno.EACCES, "denied")),
    )
    with pytest.raises(OSError):
        q.submit(SimRequest(ra=1e4, horizon=0.1))


# -- idempotency keys ---------------------------------------------------------


def test_idempotency_key_dedupes_across_queue_reopen(tmp_path):
    q = DurableQueue(str(tmp_path / "q"))
    first = SimRequest(ra=1e4, horizon=0.1, idempotency_key="job-42")
    q.submit(first)
    retry = SimRequest(ra=1e4, horizon=0.1, idempotency_key="job-42")
    q.submit(retry)
    assert retry.deduped and retry.id == first.id
    assert q.counts()["queued"] == 1  # nothing new enqueued
    # the index is durable: a fresh queue over the same dir still dedupes
    q2 = DurableQueue(str(tmp_path / "q"))
    retry2 = SimRequest(ra=1e4, horizon=0.1, idempotency_key="job-42")
    q2.submit(retry2)
    assert retry2.deduped and retry2.id == first.id
    # different key -> ordinary admission
    other = SimRequest(ra=1e4, horizon=0.1, idempotency_key="job-43")
    q2.submit(other)
    assert not getattr(other, "deduped", False) and other.id != first.id


def test_idempotency_key_validation():
    from rustpde_mpi_tpu.serve.request import RequestError

    for bad in ("", 7, "x" * 257):
        with pytest.raises(RequestError, match="idempotency_key"):
            SimRequest(ra=1e4, horizon=0.1, idempotency_key=bad).validate()
    SimRequest(ra=1e4, horizon=0.1, idempotency_key="ok").validate()


def _serve_cfg(tmp_path, **kw):
    kw.setdefault("run_dir", str(tmp_path / "serve"))
    kw.setdefault("slots", 2)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("checkpoint_every_s", None)
    kw.setdefault("http_port", None)
    return ServeConfig(**kw)


def test_server_dedupes_before_admission_policy(tmp_path):
    """A retry of already-accepted work must get its ack back even
    through a FULL queue: the dedupe check runs before every admission
    bound, so backpressure cannot 429 an idempotent replay."""
    srv = SimServer(_serve_cfg(tmp_path, max_queue=2))
    req = dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1,
               idempotency_key="retry-me")
    first = srv.submit(dict(req))
    srv.submit(dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1))
    with pytest.raises(AdmissionError):  # queue now full for NEW work
        srv.submit(dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1))
    replay = srv.submit(dict(req))
    assert replay.deduped and replay.id == first.id
    assert replay.trace_id == first.trace_id
    names = [e.get("event") for e in _events(tmp_path / "serve")]
    assert "request_deduped" in names


def test_http_front_deduped_200_and_storage_full_503(tmp_path, monkeypatch):
    from rustpde_mpi_tpu.serve.http_front import HttpFront

    srv = SimServer(_serve_cfg(tmp_path))
    front = HttpFront(srv)
    front.start()
    try:
        host, port = front.address

        def post(payload):
            req = urllib.request.Request(
                f"http://{host}:{port}/requests",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req) as resp:
                    return resp.status, json.load(resp), dict(resp.headers)
            except urllib.error.HTTPError as err:
                return err.code, json.load(err), dict(err.headers)

        body = dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1,
                    idempotency_key="http-key")
        code, ack, _ = post(body)
        assert code == 202 and "deduped" not in ack
        code, ack2, _ = post(body)
        assert code == 200 and ack2["deduped"] is True
        assert ack2["id"] == ack["id"]
        # ENOSPC surfaces as 503 + Retry-After (service impaired, not the
        # client over a bound — load balancers fail over on 5xx)
        monkeypatch.setattr(
            srv.queue, "_enqueue",
            lambda req: (_ for _ in ()).throw(
                OSError(errno.ENOSPC, "No space left on device")
            ),
        )
        code, payload, headers = post(
            dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1)
        )
        assert code == 503
        assert payload["reason"] == "storage_full"
        assert int(headers["Retry-After"]) >= 1
    finally:
        front.stop()


# -- quarantine-aware carving + unhealthy heartbeat ---------------------------


def test_carve_excludes_quarantined_devices_and_waives_total_loss(tmp_path):
    import jax

    from rustpde_mpi_tpu.config import SubmeshConfig

    cfg = _serve_cfg(
        tmp_path,
        submesh=SubmeshConfig(shapes=(2,), shard_min_nx=34),
        integrity=IntegrityConfig(strikes=1),
    )
    srv = SimServer(cfg)
    devs = jax.devices()

    def key(d):
        return f"{d.platform}:{d.id}@proc{getattr(d, 'process_index', 0)}"

    led = QuarantineLedger(cfg.run_dir, strikes=1)
    led.strike(key(devs[0]), step=1, detail="shadow")
    plan = srv._carve_plan()
    planned = {key(d) for s in plan.submeshes for d in s.devices}
    if plan.default is not None:
        planned |= {key(d) for d in plan.default.devices}
    assert key(devs[0]) not in planned
    rows = [e for e in _events(tmp_path / "serve")
            if e.get("event") == "carve_excluded_quarantined"]
    assert rows and rows[-1]["waived"] is False
    # every device struck: quarantine is WAIVED — never carve an empty fleet
    for d in devs:
        led.strike(key(d), step=2, detail="shadow")
    srv._submesh_plan = None
    srv._submesh_meshes.clear()
    plan = srv._carve_plan()
    planned = {key(d) for s in plan.submeshes for d in s.devices}
    if plan.default is not None:
        planned |= {key(d) for d in plan.default.devices}
    assert key(devs[0]) in planned
    rows = [e for e in _events(tmp_path / "serve")
            if e.get("event") == "carve_excluded_quarantined"]
    assert rows[-1]["waived"] is True


# -- clock-jump hardening -----------------------------------------------------


def test_clock_monitor_one_shot_journal_and_reanchor():
    from rustpde_mpi_tpu.serve.fleet.clock import ClockMonitor

    wall, mono = [1000.0], [50.0]
    mon = ClockMonitor(wall=lambda: wall[0], mono=lambda: mono[0])
    rows = []
    assert mon.check(30.0, journal=rows.append, where="t") == 0.0
    wall[0] += 5.0
    mono[0] += 5.0  # ordinary passage of time: no skew
    assert mon.check(30.0, journal=rows.append, where="t") == 0.0
    wall[0] += 300.0  # NTP step forward, monotonic unchanged
    with pytest.warns(RuntimeWarning, match="clock stepped"):
        skew = mon.check(30.0, journal=rows.append, where="t")
    assert skew == pytest.approx(300.0)
    assert [r["event"] for r in rows] == ["clock_skew"]
    # re-anchored: the step became the new normal after one grace scan
    assert mon.check(30.0, journal=rows.append, where="t") == 0.0
    assert len(rows) == 1
    # a BACKWARD step is still compensated, but the warn/journal latch is
    # one-shot per process — later steps ride the same root cause silently
    wall[0] -= 200.0
    assert mon.check(30.0, journal=rows.append, where="t") < 0.0
    assert len(rows) == 1


def test_replica_status_clamps_negative_ages(tmp_path):
    from rustpde_mpi_tpu.serve.fleet.proxy import (
        read_replica_status,
        write_replica_heartbeat,
    )

    write_replica_heartbeat(str(tmp_path), "r0", {"slots": []})
    # a file stamped in the future (writer's clock ahead of the reader's)
    # must clamp to age 0, not go negative / mass-expire
    path = os.path.join(str(tmp_path), "replicas", "r0.json")
    future = os.path.getmtime(path) + 3600.0
    os.utime(path, (future, future))
    (status,) = read_replica_status(str(tmp_path), ttl_s=10.0)
    assert status["hb_age_s"] == 0.0
    assert not status["stale"]


# -- cross-replica voting -----------------------------------------------------


def _done_record(run_dir, rid, digest):
    done = os.path.join(run_dir, "queue", "done")
    os.makedirs(done, exist_ok=True)
    result = {} if digest is None else {"state_digest": int(digest)}
    with open(os.path.join(done, f"{rid}.json"), "w") as fh:
        json.dump({"request": {"id": rid}, "result": result}, fh)


def test_proxy_vote_assignment_and_digest_comparison(tmp_path):
    from rustpde_mpi_tpu.serve.fleet.proxy import FleetProxy

    proxy = FleetProxy(str(tmp_path), vote_rate=1.0)
    proxy_journal = os.path.join(
        str(tmp_path), "replicas", proxy.proxy_id
    )
    req = proxy.submit(
        dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1)
    )
    counts = proxy.queue.counts()
    assert counts["queued"] == 2  # original + its .vote twin
    names = [e.get("event") for e in _events(proxy_journal)]
    assert "vote_assigned" in names
    # matching digests -> match True; differing -> mismatch journaled;
    # missing digests (integrity off) -> match None, never a false alarm
    _done_record(str(tmp_path), req.id, 77)
    _done_record(str(tmp_path), f"{req.id}.vote", 77)
    _done_record(str(tmp_path), "bad", 1)
    _done_record(str(tmp_path), "bad.vote", 2)
    _done_record(str(tmp_path), "off", None)
    _done_record(str(tmp_path), "off.vote", None)
    verdicts = {v["id"]: v["match"] for v in proxy.check_votes()}
    assert verdicts == {req.id: True, "bad": False, "off": None}
    assert proxy.check_votes() == []  # each pair verdicted exactly once
    events = _events(proxy_journal)
    mism = [e for e in events if e.get("event") == "integrity_vote_mismatch"]
    assert [e["id"] for e in mism] == ["bad"]
    assert len([e for e in events if e.get("event") == "integrity_vote"]) == 3
    # voting never votes on a vote (no .vote.vote amplification)
    assert not any(r.endswith(".vote.vote.json")
                   for r in os.listdir(os.path.join(str(tmp_path), "queue",
                                                    "queued")))


def test_vote_rate_sampling_is_deterministic(tmp_path):
    from rustpde_mpi_tpu.serve.fleet.proxy import FleetProxy

    off = FleetProxy(str(tmp_path / "a"), vote_rate=0.0)
    assert not off._vote_sampled(SimRequest(ra=1e4, horizon=0.1))
    on = FleetProxy(str(tmp_path / "b"), vote_rate=1.0)
    req = SimRequest(ra=1e4, horizon=0.1)
    assert on._vote_sampled(req)
    twin = dataclasses.replace(req, id=f"{req.id}.vote")
    assert not on._vote_sampled(twin)


# -- serve-level SDC soak (single-process CPU, slow tier) ---------------------


@pytest.mark.slow
def test_serve_bitflip_quarantine_containment(tmp_path):
    """Single-process serve soak: a bitflip mid-campaign with a
    single-strike ledger must quarantine the device (journal
    ``device_quarantined``), contain via IntegrityError (journal
    ``integrity_contained``, requeue), flag the replica unhealthy, and
    still complete every request — zero lost."""
    cfg = _serve_cfg(
        tmp_path,
        max_queue=16,
        checkpoint_every_s=2.0,
        integrity=IntegrityConfig(cadence=1, strikes=1),
    )
    srv = SimServer(cfg, fault="bitflip@8")
    for seed in range(3):
        srv.submit(dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01,
                        horizon=0.1, seed=seed))
    summary = srv.serve()
    assert summary["completed"] == 3 and summary["failed"] == 0
    counts = srv.queue.counts()
    assert counts["queued"] == 0 and counts["running"] == 0
    names = [e.get("event") for e in _events(tmp_path / "serve")]
    assert "bitflip_injected" in names
    assert "integrity_mismatch" in names
    assert "device_quarantined" in names
    assert "integrity_contained" in names
    assert QuarantineLedger(cfg.run_dir, strikes=1).quarantined()
    assert srv._integrity_unhealthy
    # every done record carries the on-device digest (the vote currency)
    done_dir = os.path.join(cfg.run_dir, "queue", "done")
    for name in os.listdir(done_dir):
        with open(os.path.join(done_dir, name)) as fh:
            rec = json.load(fh)
        assert "state_digest" in rec["result"], name


@pytest.mark.slow
def test_mp_integrity_serve_host_bitflip_quarantined_zero_lost(tmp_path):
    """The acceptance soak: 2-process serve under
    ``RUSTPDE_FAULT=bitflip@<n>:host1`` — the audit catches the flip, the
    single-strike ledger quarantines, containment requeues, and every
    request completes."""
    from mp_harness import spawn_cluster

    outs = spawn_cluster(
        str(tmp_path),
        mode="integrity_serve",
        env_extra={
            "RUSTPDE_FAULT": "bitflip@6:host1",
            "RUSTPDE_MP_SERVE_REQUESTS": "3",
        },
    )
    if outs is None:
        pytest.skip("2-process cluster spawn timed out on this machine")
    with open(os.path.join(str(tmp_path), "result.json")) as fh:
        result = json.load(fh)
    assert result["nproc"] == 2
    assert result["bitflip_injected"] >= 1
    assert result["integrity_mismatch"] >= 1
    assert result["device_quarantined"] >= 1
    assert result["integrity_contained"] >= 1
    assert result["quarantined"], result
    # zero lost: everything admitted completed; nothing stranded
    assert result["completed"] == 3 and result["failed"] == 0
    assert result["queue"]["queued"] == 0
    assert result["queue"]["running"] == 0


def test_integrity_exports_and_env_knobs():
    import rustpde_mpi_tpu.integrity as integ
    from rustpde_mpi_tpu import config

    for name in ("IntegrityError", "QuarantineLedger", "digest_tree",
                 "flip_one_bit", "flip_state_bit"):
        assert hasattr(integ, name), name
    knobs = dict(config.env_knobs())
    for knob in ("RUSTPDE_INTEGRITY", "RUSTPDE_INTEGRITY_CADENCE",
                 "RUSTPDE_VOTE_RATE"):
        assert knob in knobs, knob
    assert threading  # imported for parity with the serve test style
