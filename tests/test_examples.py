"""Smoke-run every example program (VERDICT r2 next #8).

Each of the 14 entry points runs in a subprocess on tiny grids (CPU forced
the same way tests/conftest.py does it) and must exit 0 — so the example
layer can't rot while only the models it wraps are tested.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # heavyweight end-to-end tier (VERDICT r3 #8)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# example -> fast argv (tiny grids / --quick); every program must finish in
# well under a minute on CPU
_CASES = {
    "demo_transforms.py": [],
    "solve_poisson.py": [],
    "solve_hholtz.py": ["--n", "17"],
    "navier_rbc.py": ["--quick"],
    "navier_rbc_ensemble.py": ["--quick"],
    "navier_rbc_periodic.py": ["--nx", "16", "--ny", "17", "--max-time", "0.05"],
    "navier_rbc_resilient.py": [
        "--quick", "--max-time", "0.2", "--fault", "nan@8", "--retries", "1",
    ],
    "navier_rbc_governed.py": [
        "--quick", "--max-time", "0.5", "--fault", "spike@8",
        "--spike-factor", "100", "--grow-after", "2",
    ],
    "navier_rbc_pipelined.py": ["--quick", "--max-time", "0.2"],
    "navier_rbc_serve.py": [
        "--quick", "--requests", "3", "--slots", "2", "--horizon", "0.05",
        "--run-dir", "data/serve_smoke", "--fault", "nan@3",
    ],
    "navier_rbc_roughness.py": ["--quick"],
    "navier_rbc_scenarios.py": ["--quick"],
    # an idle fleet replica in batch mode: fleet init + lease manager +
    # heartbeat publication + the idle-done handshake, then a clean exit
    "navier_rbc_fleet.py": [
        "--replica", "--replica-id", "smoke", "--run-dir", "data/fleet_smoke",
    ],
    # controller-only autoscale pass: three decide ticks over an empty
    # queue with a zero floor — exercises observe/decide/journal without
    # spawning replica subprocesses (each would pay a full JAX import)
    "navier_rbc_autoscale.py": [
        "--run-dir", "data/autoscale_smoke", "--min-replicas", "0",
        "--max-replicas", "1", "--steps", "3", "--decide-s", "0.05",
    ],
    "navier_lnse_eigenmodes.py": ["--quick", "--run-dir", "data/eig_smoke"],
    "navier_mpi.py": ["--quick"],
    "navier_rbc_steady.py": ["--quick"],
    "navier_rbc_steady_continuation.py": [
        "--nx", "17", "--ny", "17", "--num", "2", "--max-time", "2",
    ],
    "navier_lnse_test_gradient.py": ["--quick"],
    "navier_lnse_opt_reversals.py": ["--tiny"],
    "swift_hohenberg_1d.py": ["--nx", "64", "--max-time", "1.0"],
    "swift_hohenberg_2d.py": ["--quick"],
}


def test_every_example_has_a_case():
    present = sorted(
        f for f in os.listdir(os.path.join(_REPO, "examples")) if f.endswith(".py")
    )
    assert present == sorted(_CASES), "new example without a smoke case"


# the container's sitecustomize force-registers the TPU plugin and overrides
# JAX_PLATFORMS programmatically, so the CPU pin must happen in-process
# before the example's own imports (same trick as tests/conftest.py) — with
# the env var alone the smoke run would fight over the single real chip
_WRAPPER = """
import runpy, sys
import jax
jax.config.update("jax_platforms", "cpu")
path = sys.argv[1]
sys.argv = sys.argv[1:]
runpy.run_path(path, run_name="__main__")
"""


@pytest.mark.parametrize("name", sorted(_CASES))
def test_example_smoke(name, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", RUSTPDE_X64="1")
    env.pop("XLA_FLAGS", None)  # plain single-device CPU: fastest compile
    res = subprocess.run(
        [
            sys.executable,
            "-c",
            _WRAPPER,
            os.path.join(_REPO, "examples", name),
            *_CASES[name],
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(tmp_path),  # examples that write artifacts do it in cwd
        timeout=600,
    )
    assert res.returncode == 0, f"{name} rc={res.returncode}\n{res.stderr[-2500:]}"
