"""Ensemble engine tests (models/ensemble.py).

The three contract points of the batched execution engine: a K-member
vmapped step is bit-for-tolerance equivalent to K sequential solo runs (one
physics code path), a diverging member freezes without corrupting the batch
(per-member fault isolation), and buffer donation never invalidates a
reference the user retained through the public API.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rustpde_mpi_tpu import Navier2D, NavierEnsemble
from rustpde_mpi_tpu.utils.profiling import benchmark_steps


def _model(nx=17, ny=17, ra=1e4, dt=5e-3, periodic=False):
    return Navier2D(nx, ny, ra, 1.0, dt, 1.0, "rbc", periodic=periodic)


def _solo(seed, steps, **kw):
    m = _model(**kw)
    m.init_random(0.1, seed=seed)
    m.update_n(steps)
    return m


@pytest.mark.slow
def test_ensemble_matches_sequential_solo_runs():
    K, steps = 3, 7
    ens = NavierEnsemble.from_seeds(_model(), seeds=range(K))
    ens.update_n(steps)
    assert np.asarray(ens.mask).all()
    assert (np.asarray(ens.steps_done) == steps).all()
    for i in range(K):
        solo = _solo(i, steps)
        for got, want in zip(ens.member_state(i), solo.state):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-9, atol=1e-12
            )
        # per-member fused observables match the solo model's
        nu, nuvol, re, div = (v[i] for v in ens.get_observables())
        assert nu == pytest.approx(solo.eval_nu(), rel=1e-9)
        assert re == pytest.approx(solo.eval_re(), rel=1e-9)


def test_ensemble_matches_solo_periodic():
    # the split re/im Fourier layout must batch identically
    ens = NavierEnsemble.from_seeds(_model(nx=16, periodic=True), seeds=[0, 1])
    ens.update_n(5)
    solo = _solo(1, 5, nx=16, periodic=True)
    for got, want in zip(ens.member_state(1), solo.state):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-9, atol=1e-12
        )


def test_per_member_nan_isolation():
    K, steps = 3, 5
    ens = NavierEnsemble.from_seeds(_model(), seeds=range(K))
    bad = jax.tree.map(lambda x: jnp.full_like(x, jnp.nan), ens.member_state(0))
    ens.set_member(0, bad)
    ens.update_n(steps)
    mask = np.asarray(ens.mask)
    done = np.asarray(ens.steps_done)
    # the poisoned member is dead from step 0 and frozen at its IC ...
    assert not mask[0] and done[0] == 0
    assert np.isnan(np.asarray(ens.member_state(0).temp)).all()
    # ... while the others advance and match their solo runs exactly
    assert mask[1:].all() and (done[1:] == steps).all()
    for i in (1, 2):
        solo = _solo(i, steps)
        for got, want in zip(ens.member_state(i), solo.state):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-9, atol=1e-12
            )
    # observables report per member: NaN for the dead one, finite for alive
    nu = ens.eval_nu()
    assert not np.isfinite(nu[0]) and np.isfinite(nu[1:]).all()
    # graceful degradation: the batch is not dead
    assert not ens.exit()


def test_all_members_dead_triggers_exit():
    ens = NavierEnsemble.from_seeds(_model(), seeds=[0])
    ens.set_member(
        0, jax.tree.map(lambda x: jnp.full_like(x, jnp.nan), ens.member_state(0))
    )
    ens.update_n(3)
    assert ens.exit()
    assert (np.asarray(ens.steps_done) == 0).all()


def test_donation_preserves_retained_references():
    # single-run path: the donated dispatch must never touch the buffers a
    # caller retained through the public API
    model = _model()
    model.init_random(0.1, seed=0)
    s0 = model.state
    model.update_n(4)
    assert np.isfinite(np.asarray(s0.temp)).all()  # no use-after-donate
    assert model.state is not s0
    # ensemble path: state, mask and counters are all donated
    ens = NavierEnsemble.from_seeds(_model(), seeds=range(2))
    e0, m0, d0 = ens.state, ens.mask, ens.steps_done
    ens.update_n(4)
    assert np.isfinite(np.asarray(e0.temp)).all()
    assert np.asarray(m0).all() and (np.asarray(d0) == 0).all()
    assert np.isfinite(np.asarray(ens.state.temp)).all()


def test_ensemble_snapshot_roundtrip(tmp_path):
    pytest.importorskip("h5py")
    ens = NavierEnsemble.from_seeds(_model(), seeds=range(2))
    ens.update_n(3)
    fn = str(tmp_path / "ens.h5")
    ens.write(fn)
    ens2 = NavierEnsemble.from_seeds(_model(), seeds=[5, 6])
    ens2.update_n(1)
    ens2.read(fn)
    assert ens2.k == ens.k
    assert ens2.time == pytest.approx(ens.time)
    assert (np.asarray(ens2.steps_done) == np.asarray(ens.steps_done)).all()
    for attr in ("temp", "velx", "vely", "pres"):
        np.testing.assert_allclose(
            np.asarray(getattr(ens2.state, attr)),
            np.asarray(getattr(ens.state, attr)),
            rtol=1e-10,
            atol=1e-13,
        )
    # restored ensemble steps on (mask/counters consistent)
    ens2.update_n(2)
    assert np.asarray(ens2.mask).all()
    assert (np.asarray(ens2.steps_done) == 5).all()


@pytest.mark.slow
def test_profiling_reports_member_rate_and_mfu():
    from rustpde_mpi_tpu.utils.profiling import mfu_estimate

    ens = NavierEnsemble.from_seeds(_model(), seeds=range(2))
    res = benchmark_steps(ens, 2, warmup=0, reps=1)
    assert res["ensemble_size"] == 2
    assert res["member_steps_per_sec"] == pytest.approx(2 * res["steps_per_sec"])
    # ensemble step FLOPs carry the K factor (vmapped batched dot_generals)
    solo_flops = mfu_estimate(_model(), 1.0)["flops_per_step"]
    ens_flops = mfu_estimate(ens, 1.0)["flops_per_step"]
    assert ens_flops == pytest.approx(2 * solo_flops, rel=0.05)


def test_from_config_builds_k_members():
    from rustpde_mpi_tpu.config import NavierConfig

    cfg = NavierConfig(nx=17, ny=17, ra=1e4, dt=5e-3, ensemble=3)
    ens = NavierEnsemble.from_config(cfg)
    assert ens.k == 3
    # distinct seeds -> distinct members
    a = np.asarray(ens.state.temp[0])
    b = np.asarray(ens.state.temp[1])
    assert not np.allclose(a, b)
