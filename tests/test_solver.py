"""Solver-layer tests: banded kernel reconstruction, MMS Helmholtz/Poisson.

Ports the reference's testing pattern (SURVEY.md S4): small banded systems
verified by reconstruction ``A x ~= b``, and method-of-manufactured-solutions
tests with analytic fields (/root/reference/src/solver/poisson.rs:363-426,
hholtz_adi.rs:248-308).
"""

import numpy as np
import pytest

import rustpde_mpi_tpu as rp
from rustpde_mpi_tpu.ops.banded import BandedSolver, DenseSolver, banded_lu_factor
from rustpde_mpi_tpu.solver import Hholtz, HholtzAdi, Poisson


def banded_test_matrix(n, seed=0):
    """Diagonally-dominant banded matrix with offsets (-2, 0, 2, 4)."""
    rng = np.random.default_rng(seed)
    A = np.zeros((n, n))
    for off in (-2, 0, 2, 4):
        vals = rng.uniform(0.5, 1.5, n - abs(off))
        A += np.diag(vals, off)
    A += np.diag(np.full(n, 4.0))
    return A


# ---------------------------------------------------------------------------
# banded kernel
# ---------------------------------------------------------------------------


def test_banded_lu_reconstruction():
    n = 16
    A = banded_test_matrix(n)
    solver = BandedSolver(A, 2, 4)
    rng = np.random.default_rng(1)
    b = rng.standard_normal(n)
    x = np.asarray(solver.solve(b, 0))
    np.testing.assert_allclose(A @ x, b, atol=1e-10)


def test_banded_batched_matrices():
    # one factored matrix per lane (the tensor-solver pattern)
    n, m = 12, 5
    mats = np.stack([banded_test_matrix(n, seed=i) for i in range(m)])
    solver = BandedSolver(mats, 2, 4)
    rng = np.random.default_rng(2)
    b = rng.standard_normal((m, n))
    x = np.asarray(solver.solve(b, 1))
    for i in range(m):
        np.testing.assert_allclose(mats[i] @ x[i], b[i], atol=1e-10)


def test_banded_multilane_rhs():
    n, lanes = 10, 7
    A = banded_test_matrix(n, seed=3)
    solver = BandedSolver(A, 2, 4)
    rng = np.random.default_rng(3)
    b = rng.standard_normal((n, lanes))
    x = np.asarray(solver.solve(b, 0))
    np.testing.assert_allclose(A @ x, b, atol=1e-10)


def test_banded_complex_rhs():
    n = 10
    A = banded_test_matrix(n, seed=4)
    solver = BandedSolver(A, 2, 4)
    rng = np.random.default_rng(4)
    b = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    x = np.asarray(solver.solve(b, 0))
    np.testing.assert_allclose(A @ x, b, atol=1e-10)


def test_dense_matches_banded():
    n = 14
    A = banded_test_matrix(n, seed=5)
    rng = np.random.default_rng(5)
    b = rng.standard_normal((n, 3))
    xb = np.asarray(BandedSolver(A, 2, 4).solve(b, 0))
    xd = np.asarray(DenseSolver(A).solve(b, 0))
    np.testing.assert_allclose(xb, xd, atol=1e-10)


def test_lu_factor_zero_pivot_raises():
    A = np.zeros((4, 4))
    with pytest.raises(ZeroDivisionError):
        banded_lu_factor(A, 2, 4)


# ---------------------------------------------------------------------------
# Helmholtz (ADI + exact) MMS, mirroring the reference's analytic tests
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("method", ["banded", "dense"])
def test_hholtz_adi_cheb_cheb(method):
    nx, ny = 16, 17
    space = rp.Space2(rp.cheb_dirichlet(nx), rp.cheb_dirichlet(ny))
    alpha = 1e-5
    solver = HholtzAdi(space, [alpha, alpha], method=method)
    x, y = space.base_x.points, space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    n = np.pi / 2.0
    v = np.cos(n * X) * np.cos(n * Y)
    expected = v / (1.0 + alpha * n * n * 2.0)

    vhat = space.forward(v)
    sol = solver.solve(space.to_ortho(vhat))
    out = np.asarray(space.backward(sol))
    np.testing.assert_allclose(out, expected, atol=1e-3)


def test_hholtz_adi_fourier_cheb():
    nx, ny = 16, 17
    space = rp.Space2(rp.fourier_r2c(nx), rp.cheb_dirichlet(ny))
    alpha = 1e-5
    solver = HholtzAdi(space, [alpha, alpha])
    x, y = space.base_x.points, space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    n = np.pi / 2.0
    v = np.cos(X) * np.cos(n * Y)
    expected = v / (1.0 + alpha * n * n + alpha)

    vhat = space.forward(v)
    sol = solver.solve(space.to_ortho(vhat))
    out = np.asarray(space.backward(sol))
    np.testing.assert_allclose(out, expected, atol=1e-3)


def test_hholtz_exact_no_splitting_error():
    # alpha large enough that ADI splitting error would be visible; the
    # tensor-solver Helmholtz must stay exact.
    nx, ny = 24, 25
    space = rp.Space2(rp.cheb_dirichlet(nx), rp.cheb_dirichlet(ny))
    alpha = 1.0
    solver = Hholtz(space, [alpha, alpha])
    x, y = space.base_x.points, space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    n = np.pi / 2.0
    v = np.cos(n * X) * np.cos(n * Y)
    expected = v / (1.0 + alpha * n * n * 2.0)

    vhat = space.forward(v)
    sol = solver.solve(space.to_ortho(vhat))
    out = np.asarray(space.backward(sol))
    np.testing.assert_allclose(out, expected, atol=1e-9)


# ---------------------------------------------------------------------------
# Poisson MMS
# ---------------------------------------------------------------------------


def test_poisson_cheb_dirichlet():
    nx, ny = 24, 25
    space = rp.Space2(rp.cheb_dirichlet(nx), rp.cheb_dirichlet(ny))
    solver = Poisson(space, [1.0, 1.0])
    x, y = space.base_x.points, space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    n = np.pi / 2.0
    u = np.cos(n * X) * np.cos(n * Y)  # exact solution
    f = -2.0 * n * n * u  # its laplacian

    fhat = space.forward(f)
    sol = solver.solve(space.to_ortho(fhat))
    out = np.asarray(space.backward(sol))
    np.testing.assert_allclose(out, u, atol=1e-9)


def test_poisson_cheb_neumann_singular():
    # the pressure-solver configuration: pure Neumann, singular mode shifted
    nx, ny = 24, 25
    space = rp.Space2(rp.cheb_neumann(nx), rp.cheb_neumann(ny))
    solver = Poisson(space, [1.0, 1.0])
    x, y = space.base_x.points, space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    u = np.cos(np.pi * X) * np.cos(np.pi * Y)  # zero-mean, Neumann-compatible
    f = -2.0 * np.pi**2 * u

    fhat = space.forward(f)
    sol = solver.solve(space.to_ortho(fhat))
    out = np.array(space.backward(sol))
    out -= out.mean() - u.mean()  # solution defined up to a constant
    np.testing.assert_allclose(out, u, atol=1e-8)


def test_poisson_fourier_cheb():
    nx, ny = 16, 25
    space = rp.Space2(rp.fourier_r2c(nx), rp.cheb_dirichlet(ny))
    solver = Poisson(space, [1.0, 1.0])
    x, y = space.base_x.points, space.base_y.points
    X, Y = np.meshgrid(x, y, indexing="ij")
    n = np.pi / 2.0
    u = np.cos(2 * X) * np.cos(n * Y)
    f = -(4.0 + n * n) * u

    fhat = space.forward(f)
    sol = solver.solve(space.to_ortho(fhat))
    out = np.asarray(space.backward(sol))
    np.testing.assert_allclose(out, u, atol=1e-9)


def test_poisson_with_scale():
    # domain [-2, 2] x [-1, 1]: scale = [2, 1], c = 1/scale^2
    nx, ny = 24, 25
    space = rp.Space2(rp.cheb_dirichlet(nx), rp.cheb_dirichlet(ny))
    scale = [2.0, 1.0]
    solver = Poisson(space, [1.0 / scale[0] ** 2, 1.0 / scale[1] ** 2])
    x = space.base_x.points * scale[0]
    y = space.base_y.points * scale[1]
    X, Y = np.meshgrid(x, y, indexing="ij")
    n = np.pi / 2.0
    u = np.cos(n * X / scale[0]) * np.cos(n * Y)
    f = -((n / scale[0]) ** 2 + n * n) * u

    fhat = space.forward(f)
    sol = solver.solve(space.to_ortho(fhat))
    out = np.asarray(space.backward(sol))
    np.testing.assert_allclose(out, u, atol=1e-9)


def test_modal_maps_exactly_checkerboard():
    """The parity-blocked eigendecomposition must produce modal maps whose
    checkerboard zeros are exact (a full-matrix eig leaves ~1e-7-relative
    off-parity noise at n >= 1025, silently defeating fold detection)."""
    import jax.numpy as jnp

    from rustpde_mpi_tpu.bases import Space2, cheb_dirichlet, cheb_neumann
    from rustpde_mpi_tpu.ops.folded import FoldedMatrix
    from rustpde_mpi_tpu.solver import _axis_modal_data

    for ctor in (cheb_dirichlet, cheb_neumann):
        space = Space2(ctor(65), ctor(65))
        _, fwd, bwd = _axis_modal_data(space, 0, 1.0, 1.0)
        for mat in (fwd, bwd):
            r, c = mat.shape
            i = np.arange(r)[:, None]
            j = np.arange(c)[None, :]
            assert not np.any(mat[(i + j) % 2 == 1])
            assert FoldedMatrix(mat, jnp.asarray).kind == "checker"
