"""Autoscaler tests (rustpde_mpi_tpu/serve/fleet/autoscaler.py +
launcher.py): the control law against a fake launcher with injected
clocks (no subprocesses, no device work), torn-heartbeat tolerance, the
jittered Retry-After, the proxy's bearer-token gate and cross-replica
trace endpoint, the preemption-notice urgent drain, the lease-break vs
scale-in fencing race, and the autoscale-off invariant.

The full chaos soak (controller + real replica subprocesses under
Poisson preemptions) lives in the slow tier at the bottom.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from rustpde_mpi_tpu.config import (
    AutoscaleConfig,
    FleetConfig,
    ServeConfig,
)
from rustpde_mpi_tpu.serve import (
    AdmissionError,
    DurableQueue,
    FleetProxy,
    LeaseManager,
    SimRequest,
    SimServer,
)
from rustpde_mpi_tpu.serve.fleet import Autoscaler, ReplicaHandle, ReplicaLauncher
from rustpde_mpi_tpu.serve.fleet.lease import LeaseLost
from rustpde_mpi_tpu.serve.fleet.proxy import (
    read_replica_status,
    write_replica_heartbeat,
)
from rustpde_mpi_tpu.serve.http_front import rejection_payload, seed_retry_jitter
from rustpde_mpi_tpu.utils.journal import read_journal

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_REQ = dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1, bc="rbc")
_KEY = SimRequest(**_REQ).compat_key


class Clock:
    """Injectable monotonic/wall clock the control-law tests advance by
    hand — sustain windows and cooldowns become deterministic."""

    def __init__(self):
        self.t = time.monotonic()

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


class FakeLauncher(ReplicaLauncher):
    """In-memory backend: spawns are ledger entries, retire/kill are
    recorded signals — the control law is the thing under test."""

    def __init__(self):
        self._handles = {}
        self.retired_ids = []
        self.killed_ids = []

    def handles(self):
        return list(self._handles.values())

    def spawn(self, replica_id):
        h = ReplicaHandle(replica_id=replica_id, pid=1000 + len(self._handles))
        self._handles[replica_id] = h
        return h

    def retire(self, handle):
        handle.retired = True
        self.retired_ids.append(handle.replica_id)

    def kill(self, handle):
        handle.retired = True
        self.killed_ids.append(handle.replica_id)

    def alive(self, handle):
        return not getattr(handle, "dead", False)

    def reap(self):
        gone = [h for h in self._handles.values() if not self.alive(h)]
        for h in gone:
            del self._handles[h.replica_id]
        return gone


def _controller(tmp_path, cfg, clock=None, launcher=None):
    clock = clock or Clock()
    launcher = launcher or FakeLauncher()
    asc = Autoscaler(
        str(tmp_path / "fleet"),
        launcher,
        cfg,
        controller_id="asc-test",
        mono=clock,
        wall=time.time,
    )
    return asc, launcher, clock


def _decisions(run_dir):
    return read_journal(
        os.path.join(run_dir, "replicas", "asc-test", "journal.jsonl")
    )


# -- the control law (fake launcher, injected clocks) --------------------------


def test_autoscaler_sustained_queue_depth_scales_out(tmp_path):
    """Queue depth must be HIGH for sustain_s before elective scale-out
    fires; the spawned replica counts as pending capacity (spawn grace),
    and the cooldown holds the next elective action."""
    run_dir = str(tmp_path / "fleet")
    cfg = AutoscaleConfig(
        min_replicas=0, max_replicas=3, queue_high=2, sustain_s=5.0,
        cooldown_s=30.0,
    )
    asc, launcher, clock = _controller(tmp_path, cfg)
    q = DurableQueue(os.path.join(run_dir, "queue"), max_queue=64)
    for s in range(4):
        q.submit(SimRequest(**_REQ, seed=s))
    d = asc.step()
    assert (d["action"], d["reason"]) == ("hold", "pressure_building")
    clock.tick(3.0)
    assert asc.step()["action"] == "hold"  # 3s < sustain_s
    clock.tick(3.0)
    d = asc.step()
    assert (d["action"], d["reason"]) == ("scale_out", "queue_depth")
    assert len(launcher.handles()) == 1
    # the fresh spawn is pending capacity: no heartbeat yet, still counted
    clock.tick(1.0)
    d = asc.step()
    assert d["action"] == "hold" and d["pending"] == 1
    assert d["reason"] in ("cooldown", "pressure_building")
    # cooldown gates the NEXT elective scale-out even with pressure held
    clock.tick(10.0)
    d = asc.step()
    assert (d["action"], d["reason"]) == ("hold", "cooldown")
    clock.tick(30.0)
    assert asc.step()["action"] == "scale_out"
    assert asc.stats()["spawned"] == 2
    asc.stop()


def test_autoscaler_deadline_slack_scales_out_without_sustain(tmp_path):
    """A queued request whose deadline slack is under slack_low_s is an
    emergency: scale-out on the FIRST evaluation, no sustain window."""
    run_dir = str(tmp_path / "fleet")
    cfg = AutoscaleConfig(
        min_replicas=0, max_replicas=2, queue_high=50, slack_low_s=30.0
    )
    asc, launcher, _ = _controller(tmp_path, cfg)
    q = DurableQueue(os.path.join(run_dir, "queue"), max_queue=64)
    q.submit(SimRequest(**_REQ, seed=0, deadline_s=10.0))
    d = asc.step()
    assert (d["action"], d["reason"]) == ("scale_out", "deadline_slack")
    assert d["min_slack_s"] is not None and d["min_slack_s"] < 30.0
    assert len(launcher.handles()) == 1
    asc.stop()


def test_autoscaler_below_min_repair_is_immediate_and_cooldown_exempt(tmp_path):
    """Capacity repair after a preemption: a dead replica under the floor
    is replaced on the next evaluation even inside the cooldown."""
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=3, cooldown_s=3600.0)
    asc, launcher, clock = _controller(tmp_path, cfg)
    d = asc.step()
    assert (d["action"], d["reason"]) == ("scale_out", "below_min")
    h = launcher.handles()[0]
    # preemption: the replica dies hard; reap + repair on the next step
    h.dead = True
    clock.tick(1.0)
    d = asc.step()
    assert (d["action"], d["reason"]) == ("scale_out", "below_min")
    assert asc.stats()["spawned"] == 2
    asc.stop()


def test_autoscaler_idle_scale_in_drains_fewest_occupied_victim(tmp_path):
    """Scale-in fires only after a SUSTAINED fully-idle window, picks the
    launcher-owned fresh replica with the fewest occupied slots, and
    retires it through the launcher (SIGTERM semantics — the replica's
    own park-and-release drain does the work)."""
    run_dir = str(tmp_path / "fleet")
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, idle_sustain_s=10.0, cooldown_s=0.0
    )
    asc, launcher, clock = _controller(tmp_path, cfg)
    launcher.spawn("auto-a")
    launcher.spawn("auto-b")
    write_replica_heartbeat(run_dir, "auto-a", {"slots": [2, 2]})
    write_replica_heartbeat(run_dir, "auto-b", {"slots": [0, 2]})
    d = asc.step()
    assert (d["action"], d["reason"]) == ("hold", "idle_building")
    clock.tick(11.0)
    d = asc.step()
    assert (d["action"], d["reason"]) == ("scale_in", "idle")
    assert d["victim"] == "auto-b"  # fewest occupied slots drains cheapest
    assert launcher.retired_ids == ["auto-b"]
    assert launcher.killed_ids == []  # retirement is never a SIGKILL
    # the drained victim reports stopping: it leaves fresh capacity, and
    # the floor (capacity == min_replicas) blocks further scale-in
    clock.tick(11.0)
    write_replica_heartbeat(run_dir, "auto-a", {"slots": [0, 2]})
    write_replica_heartbeat(run_dir, "auto-b", {"stopping": True})
    d = asc.step()
    assert d["action"] == "hold"
    asc.stop()
    rows = _decisions(run_dir)
    retired = [r for r in rows if r["event"] == "replica_retired"]
    assert retired and retired[0]["replica"] == "auto-b"


def test_autoscaler_holds_at_max_and_journals_transitions_once(tmp_path):
    """Bounds: sustained pressure at max_replicas holds with reason
    at_max.  Hold verdicts journal only on TRANSITION — a steady
    controller must not grow the journal without bound."""
    run_dir = str(tmp_path / "fleet")
    cfg = AutoscaleConfig(
        min_replicas=0, max_replicas=1, queue_high=1, sustain_s=0.0,
        cooldown_s=0.0,
    )
    asc, launcher, clock = _controller(tmp_path, cfg)
    q = DurableQueue(os.path.join(run_dir, "queue"), max_queue=64)
    for s in range(3):
        q.submit(SimRequest(**_REQ, seed=s))
    clock.tick(1.0)
    assert asc.step()["action"] == "scale_out"
    for _ in range(5):  # at max, pressure still high: identical holds
        clock.tick(1.0)
        d = asc.step()
        assert (d["action"], d["reason"]) == ("hold", "at_max")
    asc.stop()
    rows = _decisions(run_dir)
    decisions = [r for r in rows if r["event"] == "autoscale_decision"]
    at_max = [r for r in decisions if r["reason"] == "at_max"]
    assert len(at_max) == 1, "repeated identical holds must journal once"
    assert [r["event"] for r in rows].count("replica_spawned") == 1


# -- torn heartbeats (satellite) -----------------------------------------------


def test_read_replica_status_tolerates_torn_heartbeat(tmp_path):
    """Regression: a torn/truncated heartbeat JSON is a SICK replica, not
    a missing one — stale+torn entry with a warning, while intact peers
    read normally and non-heartbeat files stay ignored."""
    run_dir = str(tmp_path / "fleet")
    write_replica_heartbeat(run_dir, "rA", {"draining": False})
    torn = os.path.join(run_dir, "replicas", "rB.json")
    with open(torn, "w", encoding="utf-8") as fh:
        fh.write('{"replica": "rB", "hb_un')  # writer died mid-record
    with open(os.path.join(run_dir, "replicas", "notes.txt"), "w") as fh:
        fh.write("not a heartbeat")
    with pytest.warns(RuntimeWarning, match="torn replica heartbeat"):
        status = read_replica_status(run_dir, ttl_s=60.0)
    assert [r["replica"] for r in status] == ["rA", "rB"]
    assert status[0]["stale"] is False and "torn" not in status[0]
    assert status[1]["stale"] is True and status[1]["torn"] is True
    # the autoscaler counts the torn replica as NOT fresh capacity
    asc = Autoscaler(
        run_dir, FakeLauncher(), AutoscaleConfig(), controller_id="asc-test"
    )
    with pytest.warns(RuntimeWarning):
        obs = asc.observe()
    assert obs["alive"] == 1 and "rB" not in obs["replicas"]
    asc.stop()


# -- jittered Retry-After (satellite) ------------------------------------------


def test_retry_after_jitter_deterministic_and_depth_scaled():
    exc = AdmissionError("queue_full", "full", retry_after_s=5.0)
    seed_retry_jitter(42)
    first = [rejection_payload(exc, 10) for _ in range(4)]
    seed_retry_jitter(42)
    second = [rejection_payload(exc, 10) for _ in range(4)]
    assert first == second  # deterministic under a pinned seed
    for payload, headers in first:
        assert payload["retry_after_s"] >= 1
        assert int(headers["Retry-After"]) == payload["retry_after_s"]
    # jitter actually varies within a seeded stream
    assert len({p["retry_after_s"] for p, _ in first}) > 1
    # deeper queues push the advice up (same draw, bigger base)
    seed_retry_jitter(7)
    shallow, _ = rejection_payload(exc, 0)
    seed_retry_jitter(7)
    deep, _ = rejection_payload(exc, 200)
    assert deep["retry_after_s"] > shallow["retry_after_s"]
    # the floor survives jitter: tiny base, many draws, never below 1
    tiny = AdmissionError("quota", "q", retry_after_s=0.2)
    seed_retry_jitter(3)
    assert all(
        rejection_payload(tiny, 0)[0]["retry_after_s"] >= 1 for _ in range(50)
    )


# -- proxy bearer-token gate (PR 15 leftover) ----------------------------------


def _post(base, payload, token=None):
    headers = {}
    if token is not None:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        base + "/requests",
        data=json.dumps(payload).encode(),
        method="POST",
        headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read()), dict(err.headers)


def test_proxy_auth_tokens_gate_mutations_only(tmp_path):
    run_dir = str(tmp_path / "fleet")
    proxy = FleetProxy(
        run_dir, max_queue=8, fleet=FleetConfig(replica_id="p1"),
        auth_tokens=["sekrit", "other"],
    )
    proxy.start()
    try:
        host, port = proxy.address
        base = f"http://{host}:{port}"
        # no credentials: 401 auth_missing with a challenge header
        code, body, headers = _post(base, dict(_REQ, seed=0))
        assert code == 401 and body["reason"] == "auth_missing"
        assert headers["WWW-Authenticate"] == "Bearer"
        # wrong token: 403 auth_invalid
        code, body, _ = _post(base, dict(_REQ, seed=0), token="wrong")
        assert code == 403 and body["reason"] == "auth_invalid"
        # either allowlisted token admits
        assert _post(base, dict(_REQ, seed=0), token="sekrit")[0] == 202
        assert _post(base, dict(_REQ, seed=1), token="other")[0] == 202
        # reads stay open: orchestrator probes carry no secrets
        with urllib.request.urlopen(base + "/healthz", timeout=30) as resp:
            assert resp.status == 200
        with urllib.request.urlopen(base + "/stats", timeout=30) as resp:
            assert resp.status == 200
        # both rejections journaled with their typed reasons
        rows = read_journal(
            os.path.join(run_dir, "replicas", "proxy-p1", "journal.jsonl")
        )
        reasons = [
            r["reason"] for r in rows if r["event"] == "auth_rejected"
        ]
        assert reasons == ["auth_missing", "auth_invalid"]
    finally:
        proxy.stop()


def test_proxy_auth_defaults_from_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("RUSTPDE_PROXY_TOKENS", "tokA, tokB")
    proxy = FleetProxy(str(tmp_path / "fleet"), max_queue=8)
    assert proxy.auth_tokens == ("tokA", "tokB")
    proxy._httpd.server_close()
    monkeypatch.setenv("RUSTPDE_PROXY_TOKENS", "")
    open_proxy = FleetProxy(str(tmp_path / "fleet2"), max_queue=8)
    assert open_proxy.auth_tokens == ()
    open_proxy._httpd.server_close()


# -- cross-replica trace assembly (PR 15 leftover) -----------------------------


def _cfg(tmp_path, **kw):
    kw.setdefault("run_dir", str(tmp_path / "fleet"))
    kw.setdefault("slots", 2)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("checkpoint_every_s", None)
    kw.setdefault("http_port", None)
    return ServeConfig(**kw)


def test_proxy_trace_endpoint_stitches_replica_journals(tmp_path):
    """GET /requests/<id>/trace on the proxy assembles the timeline from
    the replica's journal under replicas/rA/ — per-source Perfetto lanes,
    lifecycle instants, derived queued/running phases."""
    run_dir = str(tmp_path / "fleet")
    srv = SimServer(_cfg(tmp_path, fleet=FleetConfig(replica_id="rA")))
    req = srv.submit(dict(_REQ, seed=0))
    summary = srv.serve()
    assert summary["completed"] == 1
    proxy = FleetProxy(run_dir, max_queue=8, fleet=FleetConfig(replica_id="p1"))
    proxy.start()
    try:
        host, port = proxy.address
        base = f"http://{host}:{port}"
        with urllib.request.urlopen(
            f"{base}/requests/{req.id}/trace", timeout=30
        ) as resp:
            assert resp.status == 200
            payload = json.loads(resp.read())
        names = [e["name"] for e in payload["traceEvents"]]
        assert "request_admitted" in names and "request_done" in names
        assert "running" in names  # derived phase span
        assert "rA" in payload["otherData"]["lanes"].values()
        # rows carry the lane that journaled them
        lanes = {
            e["args"]["lane"]
            for e in payload["traceEvents"]
            if e["ph"] == "i" and "lane" in e.get("args", {})
        }
        assert lanes == {"rA"}
        # unknown ids 404
        try:
            urllib.request.urlopen(f"{base}/requests/nope/trace", timeout=30)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as err:
            assert err.code == 404
    finally:
        proxy.stop()


# -- preemption notice: urgent park-everything drain ---------------------------


def test_preempt_notice_sigterm_parks_and_releases(tmp_path, monkeypatch):
    """RUSTPDE_PREEMPT_NOTICE_S armed: SIGTERM mid-campaign takes the
    URGENT drain — running slots park as durable continuations with
    progress, requeue rows carry parked=True (no full checkpoint), a
    preempt_notice row lands, leases release — and a second replica
    resumes the parked request mid-flight to completion."""
    monkeypatch.setenv("RUSTPDE_PREEMPT_NOTICE_S", "20")
    run_dir = str(tmp_path / "fleet")
    srv = SimServer(
        _cfg(tmp_path, slots=1,
             fleet=FleetConfig(replica_id="rA", lease_ttl_s=60.0))
    )
    req = srv.submit(dict(_REQ, seed=0, horizon=5.0))

    def fire():
        while srv.stats()["member_steps"] < 8:
            time.sleep(0.05)
        os.kill(os.getpid(), signal.SIGTERM)

    t = threading.Thread(target=fire)
    t.start()
    summary = srv.serve()  # main thread: owns the signal handlers
    t.join()
    assert summary["completed"] == 0 and summary["failed"] == 0
    events = read_journal(
        os.path.join(run_dir, "replicas", "rA", "journal.jsonl")
    )
    names = [e["event"] for e in events]
    assert "preempt_notice" in names
    notice = next(e for e in events if e["event"] == "preempt_notice")
    assert notice["notice_s"] == 20.0 and notice["remaining_s"] > 0
    parked = [
        e for e in events
        if e["event"] == "request_requeued" and e.get("parked")
    ]
    assert parked and parked[0]["id"] == req.id
    assert parked[0].get("checkpoint") is None  # urgent: no full ckpt
    persisted = [
        e for e in events
        if e["event"] == "continuation_persisted" and e.get("steps", 0) > 0
    ]
    assert persisted, "urgent drain must park running slots durably"
    # leases released: nothing left for a survivor to break
    leases = os.listdir(os.path.join(run_dir, "queue", "leases"))
    assert [n for n in leases if n.endswith(".json")] == []
    # the request is back in the queue with its progress intact
    q = DurableQueue(os.path.join(run_dir, "queue"), max_queue=8)
    assert q.counts()["queued"] == 1
    monkeypatch.delenv("RUSTPDE_PREEMPT_NOTICE_S")
    survivor = SimServer(
        _cfg(tmp_path, fleet=FleetConfig(replica_id="rB", lease_ttl_s=60.0))
    )
    summary2 = survivor.serve()
    assert summary2["completed"] == 1 and summary2["failed"] == 0
    ev2 = read_journal(
        os.path.join(run_dir, "replicas", "rB", "journal.jsonl")
    )
    resumed = [
        e for e in ev2
        if e["event"] == "continuation_resumed" and e.get("steps", 0) > 0
    ]
    assert resumed, "survivor must resume mid-flight from the parked state"


# -- lease break racing autoscaler scale-in (satellite) ------------------------


def test_lease_break_races_scale_in_drain_fencing_order(tmp_path):
    """The race the autoscaler's scale-in opens: the victim is draining
    (its lease heartbeat already stopped) while a survivor's sweep breaks
    the same lease.  Whoever wins, fencing tokens stay strictly
    monotonic: the broken victim's release/renew/guard all raise
    LeaseLost, and the re-claim sees a strictly newer token — so a
    stalled drain write can never land over the new owner's claim."""
    root = str(tmp_path / "leases")
    victim_mgr = LeaseManager(root, "victim", ttl_s=0.1)
    survivor = LeaseManager(root, "survivor", ttl_s=0.1)
    lease = victim_mgr.claim(_KEY)
    assert lease.token == 1
    survivor.stale(lease.tag)  # open the observation window
    time.sleep(0.15)  # the draining victim stops heartbeating
    assert survivor.stale(lease.tag) is True
    broken = survivor.break_lease(lease.tag)
    assert broken is not None and broken["owner"] == "victim"
    # the victim's drain finally reaches its release: FENCED, not a crash
    with pytest.raises(LeaseLost):
        lease.release()
    with pytest.raises(LeaseLost):
        lease.guard()
    # the reclaim is strictly newer than every token the victim ever held
    relcaim = survivor.claim(_KEY)
    assert relcaim.token == 2 > broken["token"]
    relcaim.guard()
    # mirror race, other order: a clean release FIRST, then no break left
    relcaim.release()
    assert survivor.break_lease(relcaim.tag) is None


# -- the off switch: autoscale=None is byte-identical --------------------------


def test_autoscale_off_adds_nothing(tmp_path):
    """ServeConfig.autoscale defaults to None: no controller thread, no
    autoscale_* journal rows, no controller journal dir, no autoscale
    stats key — fleet serving byte-identical to PR 15."""
    assert ServeConfig().autoscale is None  # the default IS off
    run_dir = str(tmp_path / "fleet")
    srv = SimServer(_cfg(tmp_path, fleet=FleetConfig(replica_id="rA")))
    srv.submit(dict(_REQ, seed=0))
    seen_threads = set()
    done_evt = threading.Event()

    def watch():
        while not done_evt.is_set():
            seen_threads.update(t.name for t in threading.enumerate())
            time.sleep(0.02)

    t = threading.Thread(target=watch)
    t.start()
    summary = srv.serve()
    done_evt.set()
    t.join()
    assert summary["completed"] == 1
    assert "fleet-autoscale" not in seen_threads
    assert "autoscale" not in summary["fleet"]
    events = read_journal(
        os.path.join(run_dir, "replicas", "rA", "journal.jsonl")
    )
    assert [e for e in events if e["event"].startswith("autoscale")] == []
    assert [e for e in events if e["event"] == "preempt_notice"] == []
    dirs = os.listdir(os.path.join(run_dir, "replicas"))
    assert [d for d in dirs if d.startswith("autoscaler")] == []


# -- chaos soak: autoscaled fleet under Poisson preemptions (slow tier) --------


@pytest.mark.slow
def test_autoscale_chaos_soak_preemptions_loss_free(tmp_path):
    """The acceptance gate: the standalone controller scales a real
    replica fleet for a seeded backlog while the chaos schedule preempts
    replicas (notice-SIGTERM + hard SIGKILL mix) — every request reaches
    done, zero failed, and at least one request was reclaimed WITH state
    (continuation_resumed steps > 0 in some replica's journal)."""
    run_dir = str(tmp_path / "fleet")
    os.makedirs(run_dir, exist_ok=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu", RUSTPDE_X64="1")
    env.pop("RUSTPDE_FAULT", None)
    res = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "examples", "navier_rbc_autoscale.py"),
            "--run-dir", run_dir, "--requests", "4", "--seed", "7",
            "--horizon", "1.5",
            "--min-replicas", "1", "--max-replicas", "2",
            "--queue-high", "1", "--sustain-s", "1", "--cooldown-s", "2",
            "--decide-s", "0.5", "--notice-s", "8",
            "--lease-ttl-s", "3", "--heartbeat-s", "0.2",
            "--chunk-steps", "8",
            "--chaos-preempts", "2", "--chaos-kill-frac", "0.5",
            "--chaos-mean-gap-s", "1",
        ],
        capture_output=True, text=True, env=env, cwd=_REPO, timeout=1200,
    )
    assert res.returncode == 0, res.stderr[-2500:]
    lines = [json.loads(x) for x in res.stdout.splitlines() if x.startswith("{")]
    final = lines[-1]
    assert final["outcome"] == "done" and final["spawned"] >= 1
    assert final["notice"] + final["kill"] >= 1, "chaos never fired"
    counts = DurableQueue(
        os.path.join(run_dir, "queue"), max_queue=64
    ).counts()
    assert counts == {"queued": 0, "running": 0, "done": 4, "failed": 0}
    # reclaimed WITH state: some replica resumed a parked continuation
    resumed = []
    rroot = os.path.join(run_dir, "replicas")
    for name in os.listdir(rroot):
        jpath = os.path.join(rroot, name, "journal.jsonl")
        if not os.path.isfile(jpath):
            continue
        resumed += [
            e for e in read_journal(jpath, on_error="skip")
            if e["event"] == "continuation_resumed" and e.get("steps", 0) > 0
        ]
    assert resumed, "no request was ever reclaimed mid-flight"
