"""Multi-model workload subsystem tests (rustpde_mpi_tpu/workloads/ +
models/campaign.py): the CampaignModel protocol across dns/lnse/adjoint,
solo-vs-ensemble equivalence of the ported models (including across a
drain/restore cycle), the eigenmode-sweep and steady-find workload gates,
and the scenario step modifiers (passive scalar, rotating frame, vmapped
geometry sweep) with their analytic validation cases."""

import os

import numpy as np
import pytest

from rustpde_mpi_tpu import (
    MeanFields,
    Navier2D,
    Navier2DAdjoint,
    Navier2DLnse,
    NavierEnsemble,
    ScenarioConfig,
    SimRequest,
)
from rustpde_mpi_tpu.config import IOConfig
from rustpde_mpi_tpu.models.navier import scenario_signature
from rustpde_mpi_tpu.utils.resilience import ResilientRunner
from rustpde_mpi_tpu.workloads import (
    build_model,
    build_model_for_key,
    critical_rayleigh,
    eigenmode_sweep,
    geometry_sweep,
    growth_rates,
    model_kinds,
    solo_ensemble_parity,
    steady_state_find,
    validate_campaign_model,
)

h5py = pytest.importorskip("h5py")

_ARGS = dict(nx=17, ny=17, ra=1e4, pr=1.0, dt=0.01, aspect=1.0, bc="rbc")


def _dns(**kw):
    args = {**_ARGS, **kw}
    m = Navier2D(
        args["nx"], args["ny"], args["ra"], args["pr"], args["dt"],
        args["aspect"], args["bc"], periodic=False,
        scenario=args.get("scenario"),
    )
    m.set_velocity(0.1, 1.0, 1.0)
    m.set_temperature(0.1, 1.0, 1.0)
    m.write_intervall = 1e9
    return m


def _lnse():
    m = Navier2DLnse.new_confined(
        17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", mean=MeanFields.new_rbc(17, 17)
    )
    m.write_intervall = 1e9
    return m


# -- the CampaignModel protocol ----------------------------------------------


def test_campaign_model_protocol_all_kinds():
    """Every registered kind builds a model satisfying the full contract,
    with a kind-prefixed compat key that round-trips through the registry's
    key-based builder (the serve scheduler's campaign constructor)."""
    assert set(model_kinds()) >= {"dns", "lnse", "adjoint"}
    for kind in model_kinds():
        model = build_model(kind, 17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", False)
        assert validate_campaign_model(model) == [], kind
        key = model.compat_key
        assert key[0] == kind and len(key) == 10
        rebuilt = build_model_for_key(key)
        assert rebuilt.compat_key == key
    with pytest.raises(KeyError, match="unknown model kind"):
        build_model("nope", 17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", False)


def test_scenario_signature_canonical():
    """Dataclass and request-dict forms sign identically; defaults sign
    empty (equal to no scenario); modifiers re-bucket compat keys."""
    assert scenario_signature(None) == ()
    assert scenario_signature(ScenarioConfig()) == ()
    assert scenario_signature({"coriolis": 0.0}) == ()
    cfg = ScenarioConfig(coriolis=2.0, passive_scalar=True)
    assert scenario_signature(cfg) == scenario_signature(cfg.to_dict())
    assert cfg.signature == (("coriolis", 2.0), ("passive_scalar", 0.0))

    plain = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    rot = Navier2D(
        17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False,
        scenario=ScenarioConfig(coriolis=1.0),
    )
    assert plain.compat_key != rot.compat_key
    assert rot.compat_key == build_model_for_key(rot.compat_key).compat_key
    # requests sign the same way — scenario traffic buckets separately
    req = SimRequest(ra=1e4, horizon=0.1, nx=17, ny=17, dt=0.01,
                     scenario={"coriolis": 1.0})
    assert req.compat_key == rot.compat_key


# -- scenario modifiers: analytic validation ----------------------------------


def test_passive_scalar_mirrors_temperature_exactly():
    """The new-physics validation case (exact): a passive scalar at matched
    diffusivity with the temperature's BC lift, released equal to the
    temperature, stays identically equal — same advection-diffusion
    operator, same boundary forcing, machine-precision agreement."""
    m = _dns(scenario=ScenarioConfig(passive_scalar=True))
    m.set_field("scal", m.get_field("temp"))
    m.update_n(50)
    t = m.get_field("temp")
    c = m.get_field("scal")
    np.testing.assert_allclose(c, t, atol=1e-13)
    # and the scalar leaf rides snapshots (gathered layout)
    assert ("scal", "scal") in m.snapshot_vars
    # the Sherwood observable (scalar-transfer analog of the plate-flux
    # Nu) joins the vocabulary AFTER the conventional four — |div| stays
    # the index-3 NaN detector — and the mirror identity transfers:
    # matched diffusivity + equal release => sherwood == nu to fp noise
    assert m.observable_names == ("nu", "nuvol", "re", "div", "sherwood")
    obs = m.get_observables()
    assert len(obs) == 5
    assert obs[4] == pytest.approx(obs[0], rel=1e-11)


def test_passive_scalar_with_distinct_kappa_diverges_from_temp():
    """At a different scalar diffusivity the mirror breaks — the scalar is
    genuinely its own field, not an aliased temperature."""
    ka = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False).params["ka"]
    m = _dns(scenario=ScenarioConfig(passive_scalar=True, scalar_kappa=3.0 * ka))
    m.set_field("scal", m.get_field("temp"))
    m.update_n(30)
    diff = np.abs(m.get_field("scal") - m.get_field("temp")).max()
    assert np.isfinite(diff) and diff > 1e-6


def test_coriolis_absorbed_by_pressure():
    """The rotating-frame validation case: in incompressible 2-D flow the
    f-plane Coriolis force is irrotational (curl = -f div u = 0), so the
    velocity/temperature trajectory matches the non-rotating run (to the
    scheme's splitting error) while the PRESSURE absorbs the geostrophic
    correction — a large, O(1) relative change.  Measured at f=2, 50 steps:
    vel/temp drift ~1e-5, pressure drift ~0.6."""
    base = _dns()
    rot = _dns(scenario=ScenarioConfig(coriolis=2.0))
    base.update_n(50)
    rot.update_n(50)

    def rel(name):
        a, b = base.get_field(name), rot.get_field(name)
        return np.abs(a - b).max() / max(np.abs(a).max(), 1e-300)

    for name in ("velx", "vely", "temp"):
        assert rel(name) < 1e-3, name
    assert rel("pres") > 1e-2  # the force went SOMEWHERE: into the pressure
    # f=0 compiles the unmodified program: bit-equal to no scenario at all
    zero = _dns(scenario=ScenarioConfig(coriolis=0.0))
    zero.update_n(50)
    for name in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_array_equal(
            np.asarray(getattr(zero.state, name)),
            np.asarray(getattr(base.state, name)),
        )


def test_geometry_sweep_matches_solo_set_solid():
    """The vmapped solid-mask geometry sweep: K obstacle geometries stepped
    as one donated vmapped scan each match a solo ``set_solid`` run — the
    penalize-after-step factoring is an identity, not an approximation."""
    from rustpde_mpi_tpu.models.solid_masks import solid_cylinder_inner

    template = _dns()
    xs, ys = (b.points for b in template.field_space.bases)
    geoms = [
        solid_cylinder_inner(xs, ys, 0.0, 0.0, 0.3),
        solid_cylinder_inner(xs, ys, 0.4, -0.2, 0.2),
    ]
    steps = 5
    final, obs = geometry_sweep(template, geoms, steps)
    assert obs[0].shape == (2,)
    for i, (mask, value) in enumerate(geoms):
        solo = _dns()
        solo.set_solid(mask, value)
        solo.update_n(steps)
        for name in ("temp", "velx", "vely", "pres", "pseu"):
            np.testing.assert_allclose(
                np.asarray(getattr(final, name)[i]),
                np.asarray(getattr(solo.state, name)),
                rtol=1e-9, atol=1e-13,
            )
    with pytest.raises(ValueError, match="plain template"):
        solo = _dns()
        solo.set_solid(geoms[0][0])
        geometry_sweep(solo, geoms, 1)


# -- solo-vs-ensemble equivalence of the ported models ------------------------


def test_lnse_ensemble_matches_solo_and_survives_restore(tmp_path):
    """lnse as a campaign model: a K=2 vmapped ensemble's member states and
    energy observables match solo runs to the Navier-ensemble tolerance —
    INCLUDING across a drain (checkpoint) / restore cycle through the
    sharded writer under ResilientRunner."""
    mean = MeanFields.new_rbc(17, 17)

    def solo_state(seed, steps):
        solo = Navier2DLnse.new_confined(
            17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", mean=mean
        )
        solo.init_random(1e-3, seed=seed)
        solo.update_n(steps)
        return solo

    def members(model):
        out = []
        for seed in (0, 1):
            model.init_random(1e-3, seed=seed)
            out.append(model.state)
        return out

    run_dir = str(tmp_path / "lnse_campaign")
    model = Navier2DLnse.new_confined(
        17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", mean=mean
    )
    ens = NavierEnsemble(model, members(model))
    io = IOConfig(sharded_checkpoints=True, overlap_dispatch=False)
    runner = ResilientRunner(
        ens, max_time=float("inf"), run_dir=run_dir,
        checkpoint_every_s=None, io=io,
    )
    with runner.session(install_signals=False, resume=False):
        runner.advance(10)
        assert runner.checkpoint_now("drain")  # the drain half

    # a NEW incarnation restores mid-trajectory and continues
    model2 = Navier2DLnse.new_confined(
        17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", mean=mean
    )
    ens2 = NavierEnsemble(model2, members(model2))
    runner2 = ResilientRunner(
        ens2, max_time=float("inf"), run_dir=run_dir,
        checkpoint_every_s=None, io=io,
    )
    with runner2.session(install_signals=False):
        assert runner2.resumed and runner2.step == 10
        runner2.advance(10)
    for i, seed in enumerate((0, 1)):
        solo = solo_state(seed, 20)
        for got, want in zip(ens2.member_state(i), solo.state):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-9, atol=1e-12
            )
        energy = float(np.asarray(ens2.get_observables()[0])[i])
        assert energy == pytest.approx(solo.get_observables()[0], rel=1e-9)


def test_adjoint_ensemble_matches_solo_residual_trajectory():
    """The steady-adjoint as a campaign model: a vmapped K=2 ensemble's
    per-member residual trajectories match solo finds to the pinned
    ensemble tolerance at every sampled chunk boundary."""

    def build(i):
        m = Navier2DAdjoint.new_confined(17, 17, 5e3, 1.0, 5e-3, 1.0, "rbc")
        m.set_temperature(0.3 + 0.2 * i, 1.0, 1.0)
        m.set_velocity(0.3 + 0.2 * i, 1.0, 1.0)
        return m

    model = build(0)
    states = [build(i).state for i in range(2)]
    ens = NavierEnsemble(model, states)
    solos = [build(i) for i in range(2)]
    for _ in range(3):
        ens.update_n(30)
        res_ens = np.asarray(ens.get_observables()[0])
        for i, solo in enumerate(solos):
            solo.update_n(30)
            assert res_ens[i] == pytest.approx(solo.residual(), rel=1e-9)
    for i, solo in enumerate(solos):
        for got, want in zip(ens.member_state(i), solo.state):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-9, atol=1e-12
            )


def test_adjoint_convergence_freezes_scan():
    """The residual-based exit sentinel: a converged member freezes INSIDE
    the scanned chunk (steps_done stalls, done_ok reports success, the
    batch exit fires) instead of burning GEMMs past convergence."""
    model = Navier2DAdjoint(
        17, 17, 100.0, 1.0, 1e-3, 1.0, "rbc", periodic=False, res_tol=1e-5
    )
    ens = NavierEnsemble(model, [model.state])
    ens.update_n(800)  # converges well before 800 at Ra=100 from rest
    done = int(np.asarray(ens.steps_done)[0])
    assert done < 800  # froze mid-chunk at convergence
    assert ens.done_ok_members()[0]
    assert not ens.alive()[0]  # stopped advancing...
    assert ens.state_healthy()  # ...but the state is an ANSWER, not a corpse
    assert ens.exit()  # the campaign's exit sentinel fired
    res = float(np.asarray(ens.get_observables()[0])[0])
    assert res < 1e-5


def test_workloads_parity_probe():
    """The PARITY.json recorder's numbers: per-kind solo-vs-ensemble drift
    is at numerical noise for every registered model."""
    deltas = solo_ensemble_parity(steps=5)
    assert set(deltas) == {"dns", "lnse", "adjoint"}
    for kind, row in deltas.items():
        assert row["max_rel_diff"] < 1e-9, (kind, row)


# -- the eigenmode-sweep workload ---------------------------------------------


def test_eigenmode_growth_rate_signs(tmp_path):
    """Tier-1 sibling of the Ra_c gate: far below onset the leading growth
    rate is negative, far above it positive (periodic-x rigid-rigid layer
    at the critical wavelength)."""
    res = eigenmode_sweep(
        [800.0, 4000.0], nx=8, ny=17, dt=0.05, horizon=16.0, samples=8,
        run_dir=str(tmp_path / "eig"),
    )
    assert res[0]["sigma_max"] < 0.0 < res[1]["sigma_max"]
    # a completed Ra campaign sweeps its spent checkpoints: a RERUN over
    # the same directory measures fresh instead of "resuming" complete
    # with zero samples (which would report NaN rates)
    res2 = eigenmode_sweep(
        [800.0], nx=8, ny=17, dt=0.05, horizon=16.0, samples=8,
        run_dir=str(tmp_path / "eig"),
    )
    assert not res2[0]["resumed"]
    assert np.isfinite(res2[0]["sigma_max"]) and res2[0]["sigma_max"] < 0.0
    # growth_rates flags members whose energy went bad instead of lying
    bad = growth_rates([0.0, 1.0, 2.0], np.asarray([[1.0], [np.nan], [1.0]]))
    assert np.isnan(bad[0])


@pytest.mark.slow
def test_eigenmode_sweep_reproduces_critical_rayleigh(tmp_path):
    """The workload gate: the lnse eigenmode sweep's leading growth rate
    changes sign at the rigid-rigid critical Rayleigh number Ra_c = 1707.76
    (Chandrasekhar; periodic-x box at the critical wavelength) within
    discretization tolerance — measured 1727.8 (1.2%) at ny=17."""
    res = eigenmode_sweep(
        [1500.0, 1650.0, 1800.0, 1950.0],
        nx=8, ny=17, dt=0.05, horizon=40.0, samples=16,
        run_dir=str(tmp_path / "eig"),
    )
    sigmas = [r["sigma_max"] for r in res]
    assert all(np.isfinite(sigmas))
    assert sigmas == sorted(sigmas)  # growth rate increases with Ra
    rac = critical_rayleigh(res)
    assert rac == pytest.approx(1707.762, rel=0.05)


# -- the steady-find workload -------------------------------------------------


def test_steady_find_kill_resume_converges(tmp_path):
    """Tier-1 kill/resume gate: the steady finder is preempted mid-find by
    a kill fault (checkpoint-then-exit through the sharded writer) and the
    re-invocation RESUMES the same descent mid-trajectory and converges
    (modest tolerance here; the reference-threshold gate is the slow-tier
    sibling below)."""
    run_dir = str(tmp_path / "steady")
    common = dict(
        nx=17, ny=17, ra=100.0, dt=1e-3, res_tol=1e-5, k=1, amp=0.005,
        max_iters=2500, chunk=200, run_dir=run_dir, install_signals=True,
    )
    r1 = steady_state_find(**common, fault="kill@400")
    assert r1["preempted"] and r1["checkpoint"]
    assert r1["iterations"] >= 400
    assert not all(r1["converged"])

    r2 = steady_state_find(**common)
    assert r2["resumed"]  # continued the SAME descent, not a fresh start
    assert r2["iterations"] > r1["iterations"]
    assert all(r2["converged"]), r2
    assert all(res < 1e-5 for res in r2["residuals"])
    # Ra=100 << Ra_c: the steady state is conduction, Nu -> 1
    for nu in r2["nu"]:
        assert nu == pytest.approx(1.0, abs=1e-3)
    # the journal names both incarnations' lifecycles
    from rustpde_mpi_tpu.utils.journal import read_journal

    events = [e["event"] for e in read_journal(os.path.join(run_dir, "journal.jsonl"))]
    assert "checkpoint" in events and "resumed" in events


@pytest.mark.slow
def test_steady_find_reference_threshold_through_kill(tmp_path):
    """The full workload gate: a K=2 find (LSC-mode + random IC members)
    killed mid-descent resumes and converges EVERY member's residual below
    the reference threshold RES_TOL = 1e-7 (steady_adjoint.rs:60), landing
    on the conduction state (Nu = 1) at Ra = 100."""
    run_dir = str(tmp_path / "steady_ref")
    common = dict(
        nx=17, ny=17, ra=100.0, dt=1e-3, res_tol=1e-7, k=2, amp=0.005,
        max_iters=8000, chunk=250, run_dir=run_dir, install_signals=True,
    )
    r1 = steady_state_find(**common, fault="kill@500")
    assert r1["preempted"] and not all(r1["converged"])
    r2 = steady_state_find(**common)
    assert r2["resumed"] and all(r2["converged"]), r2
    assert all(res < 1e-7 for res in r2["residuals"])
    for nu in r2["nu"]:
        assert nu == pytest.approx(1.0, abs=1e-4)
