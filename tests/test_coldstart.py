"""Cold-start elimination tests (PR 19): the persistent compile cache
arming contract, admission canonicalization (dt ladder snap + slot
rounding + result parity), warm campaign pool accounting, AOT bucket
executables, and cross-process compile-cache reuse.

The fast tier drives the WarmPool directly with a stub build callback and
the scheduler's canonicalization hooks on the shared 17^2 jit shapes; the
subprocess cache-reuse test times ONLY the jit compile inside each child
(imports excluded) with a deliberately lenient gate, and the full
replica-boots-warm soak rides the slow tier.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from rustpde_mpi_tpu import config
from rustpde_mpi_tpu.config import CanonicalConfig, ServeConfig
from rustpde_mpi_tpu.serve import SimServer
from rustpde_mpi_tpu.serve.warmpool import (
    WarmPool,
    freeze_key,
    learn_profile,
    load_profile,
    save_profile,
)
from rustpde_mpi_tpu.telemetry import compile_log
from rustpde_mpi_tpu.utils.journal import read_journal

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_REQ = dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1, bc="rbc")

_CACHE_VARS = (
    "JAX_COMPILATION_CACHE_DIR",
    "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES",
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
    "RUSTPDE_COMPILE_CACHE",
    "RUSTPDE_COMPILE_CACHE_DIR",
)


@pytest.fixture
def cache_env():
    """Snapshot/restore the cache arming state: the env vars, the module
    idempotence latch, and jax's own cache-dir config — so these tests
    can arm/disarm freely without leaking into the rest of the tier."""
    import jax

    saved = {name: os.environ.get(name) for name in _CACHE_VARS}
    saved_latch = config._cache_armed
    saved_jax = jax.config.jax_compilation_cache_dir
    yield
    for name, val in saved.items():
        if val is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = val
    config._cache_armed = saved_latch
    jax.config.update("jax_compilation_cache_dir", saved_jax)


def _cfg(tmp_path, **kw):
    kw.setdefault("run_dir", str(tmp_path / "serve"))
    kw.setdefault("slots", 2)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("checkpoint_every_s", None)
    kw.setdefault("http_port", None)
    return ServeConfig(**kw)


# -- compile cache arming -----------------------------------------------------


def test_ensure_compile_cache_arms_once(tmp_path, cache_env):
    config._cache_armed = None
    os.environ.pop("RUSTPDE_COMPILE_CACHE", None)
    os.environ["RUSTPDE_COMPILE_CACHE_DIR"] = str(tmp_path / "cache")
    first = config.ensure_compile_cache()
    assert first == str(tmp_path / "cache")
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == first
    # idempotent: the second call returns the latched path without
    # re-reading the knobs (a changed dir mid-process must not re-arm)
    os.environ["RUSTPDE_COMPILE_CACHE_DIR"] = str(tmp_path / "elsewhere")
    assert config.ensure_compile_cache() == first


def test_ensure_compile_cache_knob_off_is_inert(tmp_path, cache_env):
    config._cache_armed = None
    os.environ["RUSTPDE_COMPILE_CACHE"] = "0"
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    assert config.ensure_compile_cache() is None
    assert "JAX_COMPILATION_CACHE_DIR" not in os.environ
    assert config._cache_armed is None


def test_compile_cache_env_snapshot(tmp_path, cache_env):
    config._cache_armed = None
    os.environ.pop("RUSTPDE_COMPILE_CACHE", None)
    os.environ["RUSTPDE_COMPILE_CACHE_DIR"] = str(tmp_path / "cache")
    config.ensure_compile_cache()
    env = config.compile_cache_env()
    assert env["JAX_COMPILATION_CACHE_DIR"] == str(tmp_path / "cache")
    assert "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES" in env


def test_launcher_seeds_cache_env_into_custom_snapshot(tmp_path, cache_env):
    from rustpde_mpi_tpu.serve.fleet.launcher import LocalProcessLauncher

    config._cache_armed = None
    os.environ.pop("RUSTPDE_COMPILE_CACHE", None)
    os.environ["RUSTPDE_COMPILE_CACHE_DIR"] = str(tmp_path / "cache")
    armed = config.ensure_compile_cache()
    # a custom env snapshot missing the arming vars gets them seeded, so
    # every spawned replica shares the fleet cache; an explicit value in
    # the snapshot wins (setdefault)
    launcher = LocalProcessLauncher(
        str(tmp_path / "fleet"),
        env={"PATH": os.environ.get("PATH", ""),
             "RUSTPDE_COMPILE_CACHE": "0"},
    )
    assert launcher.env["JAX_COMPILATION_CACHE_DIR"] == armed
    assert launcher.env["RUSTPDE_COMPILE_CACHE"] == "0"


# -- admission canonicalization -----------------------------------------------


def test_canonicalize_snaps_dt_preserving_horizon(tmp_path):
    srv = SimServer(
        _cfg(tmp_path, canonicalize=CanonicalConfig(dt_anchor=1e-2))
    )
    req = srv.submit({**_REQ, "dt": 9e-3, "horizon": 0.08})
    # snapped onto rung 0 EXACTLY (the ladder float, not an approximation)
    assert req.dt == 1e-2
    # steps re-derive from horizon/dt: same physical end time, fewer steps
    assert req.steps == 8
    rows = [
        r
        for r in read_journal(os.path.join(srv.cfg.run_dir, "journal.jsonl"))
        if r.get("event") == "request_canonicalized"
    ]
    assert len(rows) == 1
    assert rows[0]["dt_from"] == 9e-3 and rows[0]["dt_to"] == 1e-2
    assert rows[0]["rung"] == 0


def test_canonicalize_co_buckets_near_rung_requests(tmp_path):
    srv = SimServer(
        _cfg(tmp_path, canonicalize=CanonicalConfig(dt_anchor=1e-2))
    )
    a = srv.submit({**_REQ, "dt": 1e-2})
    b = srv.submit({**_REQ, "dt": 9e-3})
    assert a.compat_key == b.compat_key


def test_canonicalize_on_rung_dt_untouched(tmp_path):
    srv = SimServer(
        _cfg(tmp_path, canonicalize=CanonicalConfig(dt_anchor=1e-2))
    )
    req = srv.submit({**_REQ, "dt": 1e-2})
    assert req.dt == 1e-2
    events = [
        r.get("event")
        for r in read_journal(os.path.join(srv.cfg.run_dir, "journal.jsonl"))
    ]
    assert "request_canonicalized" not in events


def test_canonicalize_shift_bound_keeps_exact_dt(tmp_path):
    # 3e-3 would snap to the 2.5e-3 rung (-17%), beyond a 0.1 bound: the
    # request keeps its exact dt and pays its own compile
    srv = SimServer(
        _cfg(
            tmp_path,
            canonicalize=CanonicalConfig(dt_anchor=1e-2, max_rel_dt_shift=0.1),
        )
    )
    req = srv.submit({**_REQ, "dt": 3e-3})
    assert req.dt == 3e-3


def test_canonicalize_off_is_inert(tmp_path):
    srv = SimServer(_cfg(tmp_path))
    req = srv.submit({**_REQ, "dt": 9e-3})
    assert req.dt == 9e-3
    assert srv._canon_ladder is None


def test_canonical_k_rounds_up_to_pool_size(tmp_path):
    canon = CanonicalConfig(slot_sizes=(2, 4, 8))
    assert SimServer(
        _cfg(tmp_path / "a", slots=3, canonicalize=canon)
    )._canonical_k() == 4
    # above every pool size: the largest pool wins (lanes are bounded)
    assert SimServer(
        _cfg(tmp_path / "b", slots=16, canonicalize=canon)
    )._canonical_k() == 8
    assert SimServer(_cfg(tmp_path / "c", slots=3))._canonical_k() == 3


# -- canonicalized-vs-direct parity -------------------------------------------


def _serve_one(tmp_path, name, dt, canonicalize):
    srv = SimServer(
        _cfg(tmp_path / name, canonicalize=canonicalize, slots=1)
    )
    req = srv.submit({**_REQ, "dt": dt, "horizon": 0.08, "seed": 3})
    srv.serve()
    return srv.result(req.id)


def test_canonicalized_parity_within_documented_rtol(tmp_path):
    """The canonicalization contract's physics half: a dt snapped onto
    the ladder reaches the same horizon with observables within
    ``CanonicalConfig.rtol`` of the exact-dt run."""
    canon = CanonicalConfig(dt_anchor=1e-2)
    direct = _serve_one(tmp_path, "direct", 9e-3, None)
    snapped = _serve_one(tmp_path, "snapped", 9e-3, canon)
    assert direct is not None and snapped is not None
    scale = max(abs(direct["nu"]), 1e-12)
    assert abs(snapped["nu"] - direct["nu"]) / scale <= canon.rtol


# -- warm pool ----------------------------------------------------------------


class _FakeEns:
    def __init__(self, k):
        self.k = k


def _key(tag="dns", nx=17):
    return (tag, nx, nx, 1e4, 1.0, 1e-2, 1.0, "rbc", False, ())


def test_freeze_key_normalizes_json_round_trip():
    key = _key()
    thawed = json.loads(json.dumps(list(key)))
    assert freeze_key(thawed) == key
    assert compile_log.key_tag(freeze_key(thawed)) == compile_log.key_tag(key)


def test_warm_pool_hit_miss_eviction_accounting():
    built = []

    def build(key, k):
        built.append(key)
        return object(), _FakeEns(k or 2), 1

    rows = []
    pool = WarmPool(
        [{"key": _key(), "k": 2}], build, journal=rows.append, max_entries=2
    )
    pool.start()
    assert pool.wait(timeout=10)
    assert pool.counts()["built"] == 1 and pool.counts()["pooled"] == 1

    # hit: ownership transfers, so the same key misses the second time
    got = pool.take(_key(), 2)
    assert got is not None and got[1].k == 2
    assert pool.take(_key(), 2) is None
    # unknown key: plain miss
    assert pool.take(_key(nx=33)) is None
    counts = pool.counts()
    assert counts["hits"] == 1 and counts["misses"] == 2
    events = [r["event"] for r in rows]
    assert events.count("aot_build") == 1
    assert events.count("warm_pool_hit") == 1
    assert events.count("warm_pool_miss") == 2


def test_warm_pool_k_mismatch_is_miss_and_eviction():
    pool = WarmPool([], lambda key, k: None)
    pool.put(_key(), object(), _FakeEns(2))
    assert pool.take(_key(), 4) is None
    counts = pool.counts()
    assert counts["misses"] == 1 and counts["evictions"] == 1
    assert counts["pooled"] == 0


def test_warm_pool_capacity_eviction_is_fifo():
    rows = []
    pool = WarmPool([], lambda key, k: None, journal=rows.append, max_entries=1)
    pool.put(_key(nx=17), object(), _FakeEns(2))
    pool.put(_key(nx=33), object(), _FakeEns(2))
    assert pool.counts() == {
        "hits": 0, "misses": 0, "evictions": 1, "built": 0,
        "build_errors": 0, "pooled": 1,
    }
    assert pool.take(_key(nx=33)) is not None  # newest survived
    (evict,) = [r for r in rows if r["event"] == "warm_pool_evict"]
    assert evict["reason"] == "capacity"


def test_warm_pool_take_waits_for_in_flight_build():
    """The race the wait kills: a campaign opening before the background
    builder finishes must BLOCK on the in-flight entry (the build started
    earlier, so waiting beats a duplicate inline compile), not record a
    miss and cold-build the same key twice."""
    release = threading.Event()

    def build(key, k):
        release.wait(10)
        return object(), _FakeEns(k or 2), 1

    pool = WarmPool([{"key": _key(), "k": 2}], build)
    pool.start()
    got = {}

    def taker():
        got["entry"] = pool.take(_key(), 2)

    t = threading.Thread(target=taker)
    t.start()
    t.join(0.2)
    assert t.is_alive(), "take() returned before the in-flight build finished"
    release.set()
    t.join(10)
    assert not t.is_alive() and got["entry"] is not None
    assert pool.counts()["hits"] == 1 and pool.counts()["misses"] == 0


def test_warm_pool_stop_unblocks_waiters_and_skips_entries():
    def build(key, k):
        time.sleep(0.05)
        return object(), _FakeEns(2), 1

    pool = WarmPool([{"key": _key(nx=n)} for n in (17, 33, 65)], build)
    pool.stop()  # stop BEFORE start: every entry skipped, no waiter hangs
    pool.start()
    assert pool.wait(timeout=10)
    assert pool.take(_key(nx=65)) is None  # miss, but instant — not a hang


def test_warm_pool_build_error_accounted_not_fatal():
    def build(key, k):
        if key[1] == 17:
            raise RuntimeError("boom")
        return object(), _FakeEns(2), 1

    rows = []
    pool = WarmPool(
        [{"key": _key(nx=17)}, {"key": _key(nx=33)}], build, journal=rows.append
    )
    pool.start()
    assert pool.wait(timeout=10)
    counts = pool.counts()
    assert counts["build_errors"] == 1 and counts["built"] == 1
    errs = [r for r in rows if r["event"] == "warm_pool_error"]
    assert len(errs) == 1 and "boom" in errs[0]["error"]


# -- profiles -----------------------------------------------------------------


def test_profile_load_save_round_trip(tmp_path):
    path = str(tmp_path / "profile.json")
    save_profile(path, [{"key": _key(), "k": 4}])
    entries = load_profile(path)
    assert entries == [{"key": _key(), "k": 4}]
    # inline lists pass through with the same normalization
    assert load_profile([{"key": list(_key()), "k": "4"}]) == [
        {"key": _key(), "k": 4}
    ]
    # missing/corrupt files must not stop the service from booting
    assert load_profile(str(tmp_path / "nope.json")) == []
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert load_profile(str(bad)) == []
    assert load_profile(None) == []


def test_learn_profile_ranks_by_build_count_and_skips_aot(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    rows = (
        [{"event": "compile_build", "key": list(_key(nx=17)), "k": 2,
          "phase": "build"}] * 3
        + [{"event": "compile_build", "key": list(_key(nx=33)), "k": 4,
            "phase": "build"}]
        # the pool must not learn from its own background builds
        + [{"event": "compile_build", "key": list(_key(nx=65)),
            "phase": "aot"}] * 9
        + [{"event": "request_done", "id": "x"}]
    )
    with open(path, "w") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    entries = learn_profile(path)
    assert [e["key"][1] for e in entries] == [17, 33]
    assert entries[0]["k"] == 2 and entries[1]["k"] == 4
    assert learn_profile(str(tmp_path / "missing.jsonl")) == []


# -- warm serve end to end ----------------------------------------------------


def test_warm_pool_serve_hits_with_zero_jit_builds(tmp_path):
    """The acceptance gate at test scale: with the key's campaign
    prebuilt from the profile, admission -> first chunk crosses ZERO
    compile_build rows — the warm takeover skips the jit entirely — and
    an off-rung request canonicalizes into the same warm bucket."""
    profile = [{"key": list(_REQ_KEY), "k": 2}]
    srv = SimServer(
        _cfg(
            tmp_path,
            chunk_steps=8,
            warm_profile=profile,
            canonicalize=CanonicalConfig(dt_anchor=1e-2, slot_sizes=(2,)),
        )
    )
    for seed, dt in enumerate([1e-2, 9e-3]):
        srv.submit({**_REQ, "dt": dt, "horizon": 0.08, "seed": seed})
    summary = srv.serve()
    assert summary["completed"] == 2
    events = {}
    for row in read_journal(os.path.join(srv.cfg.run_dir, "journal.jsonl")):
        events[row.get("event")] = events.get(row.get("event"), 0) + 1
    assert events.get("warm_pool_hit") == 1
    assert events.get("aot_build") == 1
    assert events.get("request_canonicalized") == 1
    assert "compile_build" not in events, "warm campaign still jit-built"


_REQ_KEY = ("dns", 17, 17, 1e4, 1.0, 1e-2, 1.0, "rbc", False, ())


def test_warm_pool_off_no_thread_no_rows(tmp_path):
    srv = SimServer(_cfg(tmp_path, chunk_steps=8))
    srv.submit({**_REQ, "horizon": 0.04})
    srv.serve()
    assert srv._warm is None
    events = [
        r.get("event")
        for r in read_journal(os.path.join(srv.cfg.run_dir, "journal.jsonl"))
    ]
    assert not any(
        e and (e.startswith("warm_pool") or e == "aot_build") for e in events
    )
    assert "compile_build" in events  # the cold path still journals builds


# -- cross-process persistent cache reuse -------------------------------------

_CHILD_COMPILE = r"""
import json, os, sys, time
import jax
import jax.numpy as jnp

def step(x):
    for _ in range(8):
        x = jnp.fft.rfft2(jnp.tanh(jnp.fft.irfft2(x, s=(48, 48))))
    return x

x = jnp.ones((48, 25), dtype=jnp.complex64)
fn = jax.jit(step)
t0 = time.perf_counter()
fn.lower(x).compile()
print(json.dumps({"compile_s": time.perf_counter() - t0}))
"""


def test_cross_process_cache_reuse(tmp_path):
    """Second process's compile of the SAME function deserializes from
    the persistent cache dir instead of recompiling.  The gate is
    deliberately lenient (CI timing noise): the cache dir must be
    populated by the first child, and the second child's compile must
    not be slower — with a real speedup asserted only when the cold
    compile was slow enough to measure."""
    cache = str(tmp_path / "cache")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "JAX_COMPILATION_CACHE_DIR": cache,
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "-1",
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
    }

    def run():
        out = subprocess.run(
            [sys.executable, "-c", _CHILD_COMPILE],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])["compile_s"]

    cold = run()
    assert os.listdir(cache), "first compile left the cache dir empty"
    warm = run()
    assert warm <= cold * 1.1 + 0.05
    if cold > 1.0:
        assert warm <= cold * 0.8


# -- replica boots warm (slow tier) -------------------------------------------


@pytest.mark.slow
def test_restarted_server_boots_warm_from_shared_cache(tmp_path):
    """Restart-to-first-result with a shared persistent cache: the second
    server process (fresh run_dir, same cache dir) rebuilds its campaign
    against serialized executables — its jit-build wall collapses vs the
    cold first boot.  This is the autoscaled-replica contract: a scale-out
    spawn inherits JAX_COMPILATION_CACHE_DIR through the launcher env and
    pays deserialization, not compilation."""
    cache = str(tmp_path / "cache")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "RUSTPDE_COMPILE_CACHE": "1",
        "RUSTPDE_COMPILE_CACHE_DIR": cache,
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
    }

    def boot(name):
        run_dir = str(tmp_path / name)
        out = subprocess.run(
            [
                sys.executable,
                os.path.join(_REPO, "examples", "navier_rbc_serve.py"),
                "--quick", "--requests", "1", "--slots", "1",
                "--horizon", "0.04", "--run-dir", run_dir,
            ],
            env=env, capture_output=True, text=True, timeout=600, cwd=_REPO,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        walls = [
            float(r.get("wall_s", 0.0))
            for r in read_journal(os.path.join(run_dir, "journal.jsonl"))
            if r.get("event") == "compile_build" and r.get("phase") == "build"
        ]
        assert walls, "no compile_build rows journaled"
        return sum(walls)

    cold = boot("first")
    warm = boot("second")
    assert os.listdir(cache)
    assert warm < cold, f"warm boot not faster: {warm:.2f}s vs {cold:.2f}s"
    if cold > 2.0:
        assert warm <= cold * 0.7
