"""True multi-*process* distributed execution (VERDICT r2 missing #2).

The reference actually runs across processes (``cargo mpirun --np 2``,
/root/reference/examples/poisson_mpi.rs); the JAX analog is one controller
per process over ``jax.distributed``.  This spawns a real 2-process CPU
cluster (gloo collectives, localhost coordinator), advances a pencil-sharded
Navier2D on the 4-device global mesh, exercises every multi-process branch
of parallel/multihost.py (initialize_distributed, host_local_array,
global_array, sync_hosts, is_root), writes a snapshot from rank 0, and
compares bit-level against a single-process run of the same model.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavyweight end-to-end tier (VERDICT r3 #8)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NPROC = 2


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def mp_result(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("mp"))
    port = _free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        RUSTPDE_X64="1",
    )
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(_REPO, "tests", "mp_worker.py"),
                str(port),
                str(i),
                str(_NPROC),
                out_dir,
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(_NPROC)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("multi-process spawn timed out in this environment")
    for rc, out, err in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{err[-3000:]}"
        assert "OK" in out
    with open(os.path.join(out_dir, "result.json")) as f:
        return json.load(f), out_dir


def test_two_process_cluster_formed(mp_result):
    result, _ = mp_result
    assert result["nproc"] == _NPROC
    assert result["ndev_global"] == 2 * _NPROC


def test_multiprocess_matches_single_process(mp_result):
    """10 sharded steps across 2 processes == the same model in-process."""
    result, _ = mp_result
    from rustpde_mpi_tpu import Navier2D

    model = Navier2D(34, 34, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.update_n(10)
    nu, nuvol, re, div = model.get_observables()
    assert result["nu"] == pytest.approx(nu, abs=1e-12)
    assert result["nuvol"] == pytest.approx(nuvol, abs=1e-12)
    assert result["re"] == pytest.approx(re, abs=1e-10)
    assert result["checksum"] == pytest.approx(
        float(np.abs(np.asarray(model.state.temp)).sum()), abs=1e-11
    )


def test_multiprocess_snapshot_written(mp_result):
    """Rank-0 snapshot from the gathered global state matches the
    single-process spectral state."""
    result, out_dir = mp_result
    h5py = pytest.importorskip("h5py")
    from rustpde_mpi_tpu import Navier2D

    model = Navier2D(34, 34, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.update_n(10)
    with h5py.File(os.path.join(out_dir, "snapshot_mp.h5")) as f:
        temp = f["temp"][...]
    np.testing.assert_allclose(temp, np.asarray(model.state.temp), atol=1e-12)
