"""True multi-*process* distributed execution (VERDICT r2 missing #2).

The reference actually runs across processes (``cargo mpirun --np 2``,
/root/reference/examples/poisson_mpi.rs); the JAX analog is one controller
per process over ``jax.distributed``.  This spawns a real 2-process CPU
cluster (gloo collectives, localhost coordinator), advances a pencil-sharded
Navier2D on the 4-device global mesh, exercises every multi-process branch
of parallel/multihost.py (initialize_distributed, host_local_array,
global_array, sync_hosts, is_root), writes a snapshot from rank 0, and
compares bit-level against a single-process run of the same model.
"""

import json
import os

import numpy as np
import pytest

from mp_harness import spawn_cluster  # tests/ dir is on sys.path under pytest

pytestmark = pytest.mark.slow  # heavyweight end-to-end tier (VERDICT r3 #8)

_NPROC = 2


def _spawn(out_dir, mode=None, env_extra=None, check=True):
    """spawn_cluster with the suite's timeout policy: a spawn timeout in
    this environment is a skip, not a failure."""
    outs = spawn_cluster(
        out_dir, mode=mode, nproc=_NPROC, env_extra=env_extra, check=check
    )
    if outs is None:
        pytest.skip("multi-process spawn timed out in this environment")
    return outs


@pytest.fixture(scope="module")
def mp_result(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("mp"))
    outs = _spawn(out_dir)
    for rc, out, err in outs:
        assert "OK" in out
    with open(os.path.join(out_dir, "result.json")) as f:
        return json.load(f), out_dir


def test_two_process_cluster_formed(mp_result):
    result, _ = mp_result
    assert result["nproc"] == _NPROC
    assert result["ndev_global"] == 2 * _NPROC


def test_multiprocess_matches_single_process(mp_result):
    """10 sharded steps across 2 processes == the same model in-process."""
    result, _ = mp_result
    from rustpde_mpi_tpu import Navier2D

    model = Navier2D(34, 34, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.update_n(10)
    nu, nuvol, re, div = model.get_observables()
    assert result["nu"] == pytest.approx(nu, abs=1e-12)
    assert result["nuvol"] == pytest.approx(nuvol, abs=1e-12)
    assert result["re"] == pytest.approx(re, abs=1e-10)
    assert result["checksum"] == pytest.approx(
        float(np.abs(np.asarray(model.state.temp)).sum()), abs=1e-11
    )


def test_multiprocess_snapshot_written(mp_result):
    """Rank-0 snapshot from the gathered global state matches the
    single-process spectral state."""
    result, out_dir = mp_result
    h5py = pytest.importorskip("h5py")
    from rustpde_mpi_tpu import Navier2D

    model = Navier2D(34, 34, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.update_n(10)
    with h5py.File(os.path.join(out_dir, "snapshot_mp.h5")) as f:
        temp = f["temp"][...]
    np.testing.assert_allclose(temp, np.asarray(model.state.temp), atol=1e-12)


# -- sharded two-phase checkpoints across real processes ----------------------


def _serial_34():
    from rustpde_mpi_tpu import Navier2D

    model = Navier2D(34, 34, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.write_intervall = 1e9
    return model


def test_sharded_two_phase_kill_then_resume(tmp_path):
    """THE two-phase durability proof across real processes:

    1. a 2-process resilient run with sharded checkpoints is killed on
       host 1 BETWEEN its shard fsync and the manifest commit
       (``RUSTPDE_SHARD_CRASH=after_shard@10:host1``) — host 0 wedges at
       the commit barrier and the sync watchdog converts that into a
       structured exit, so NO manifest for step 10 ever appears;
    2. the previous cadence checkpoint (step 5) is digest-clean and
       ``latest_checkpoint`` picks it — the aborted attempt's orphan
       shards are invisible to resume;
    3. a fresh 2-process run on the same run_dir auto-resumes from step 5
       and completes, its final sharded checkpoint verifying end-to-end;
    4. elastic restore: the final manifest (written by 2 processes over a
       4-device mesh) restores onto a plain SERIAL model in this parent
       process, bit-equal to the workers' dumped global state."""
    from rustpde_mpi_tpu.utils import checkpoint as cp

    out_dir = str(tmp_path / "mpshard")
    os.makedirs(out_dir, exist_ok=True)
    run_dir = os.path.join(out_dir, "run")

    outs = _spawn(
        out_dir,
        "sharded_run",
        env_extra={
            "RUSTPDE_SHARD_CRASH": "after_shard@10:host1",
            "RUSTPDE_SYNC_TIMEOUT_S": "30",
            "RUSTPDE_MP_BLOCKING_IO": "1",
            "RUSTPDE_SANITIZE": "1",  # armed through the kill window too
        },
        check=False,  # rcs asserted per rank below (9 / nonzero expected)
    )
    assert outs[1][0] == 9, f"host1 should die at the crash hook: {outs[1][2][-2000:]}"
    assert outs[0][0] != 0, "host0 must not report success after losing its peer"
    # no manifest for the aborted step-10 attempt; its orphan shards may exist
    assert not os.path.exists(cp.checkpoint_path(run_dir, 10))
    latest = cp.latest_checkpoint(run_dir)
    assert latest is not None
    attrs = cp.verify_snapshot(latest)  # manifest + every shard digest-clean
    assert int(attrs["step"]) == 5
    assert int(attrs["sharded"]) == _NPROC

    # clean rerun resumes from the surviving checkpoint and completes
    _spawn(out_dir, "sharded_run")
    with open(os.path.join(out_dir, "result.json")) as f:
        result = json.load(f)
    assert result["outcome"] == "done"
    assert result["step"] == 20
    events = []
    with open(os.path.join(run_dir, "journal.jsonl")) as fh:
        events = [json.loads(line) for line in fh]
    resumed = [e for e in events if e["event"] == "resumed"]
    assert resumed and resumed[-1]["step"] == 5
    sharded_ckpts = [e for e in events if e.get("checkpoint_sharded")]
    assert sharded_ckpts, "journal must carry checkpoint_sharded telemetry"
    row = sharded_ckpts[-1]["checkpoint_sharded"]
    assert row["shards"] == _NPROC and row["bytes_host"] > 0

    # elastic restore onto a serial model, bit-equal to the dumped state
    final = result["checkpoint"]
    assert int(cp.verify_snapshot(final)["step"]) == 20
    dumped = np.load(os.path.join(out_dir, "final_state.npz"))
    model = _serial_34()
    model.read(final)
    for name in model.state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(model.state, name)), dumped[name], err_msg=name
        )
    assert model.time == pytest.approx(float(dumped["time"]))


def _serve_solo_nu(result):
    """Solo serial rerun of one served request's trajectory (the 2-proc
    campaign must be member- AND topology-isolated: vmapped on a 4-device
    2-process mesh == the plain serial model, to the serve tolerance)."""
    from rustpde_mpi_tpu import Navier2D

    m = Navier2D(34, 34, 1e4, 1.0, result["dt"], 1.0, "rbc", periodic=False)
    m.init_random(result.get("amp") or 0.1, seed=result["seed"])
    m.update_n(result["steps"])
    return float(m.eval_nu())


def test_multiprocess_serve_campaign_chaos_soak(tmp_path):
    """THE multihost-serving gate (ISSUE 10 acceptance): one durable queue
    of requests served by a 2-process root-coordinated campaign through
    three failure axes —

    1. SIGTERM drain mid-campaign (``kill@`` hits every host; root
       broadcasts the stop, the sharded slot-table checkpoint commits,
       unfinished requests re-enqueue, both ranks exit clean);
    2. host-scoped SIGKILL (``kill@..:host1``): rank 1 dies mid-flight,
       rank 0's watchdogs convert the wedged collective into a structured
       nonzero exit — no manifest torn, requests stay claimed on disk;
    3. restart with a DIFFERENT slot count + batch NaN: the fleet re-plans
       (``campaign_replanned``), drained/killed trajectories restore
       mid-flight, the NaN'd batch retries at dt/2, and the queue drains.

    Zero requests lost or failed, and sampled results match solo serial
    reruns to the serve isolation tolerance."""
    import numpy as np

    from rustpde_mpi_tpu.utils.journal import read_journal

    out_dir = str(tmp_path / "mpserve")
    os.makedirs(out_dir, exist_ok=True)
    n_req = 5
    base = {
        "RUSTPDE_MP_SERVE_REQUESTS": str(n_req),
        "RUSTPDE_SYNC_TIMEOUT_S": "60",
        "RUSTPDE_DISPATCH_TIMEOUT_S": "60",
        # collective-sequence sanitizer armed through the whole chaos soak:
        # any scheduler decision reaching a collective without the root
        # plan trips a typed CollectiveDesyncError instead of passing
        "RUSTPDE_SANITIZE": "1",
    }

    # phase 1: enqueue everything, drain at step 6 (SIGTERM on every host)
    _spawn(
        out_dir,
        "serve_campaign",
        env_extra={**base, "RUSTPDE_MP_SERVE_SLOTS": "2",
                   "RUSTPDE_FAULT": "kill@6"},
    )
    with open(os.path.join(out_dir, "result.json")) as f:
        r1 = json.load(f)
    assert r1["outcome"] == "drained" and r1["requeued"] >= 1

    # phase 2: resume 2-proc, rank 1 dies HARD mid-campaign; rank 0 must
    # exit structured (watchdog), not wedge forever
    outs = _spawn(
        out_dir,
        "serve_campaign",
        env_extra={**base, "RUSTPDE_MP_SERVE_SLOTS": "2",
                   "RUSTPDE_FAULT": "kill@12:host1"},
        check=False,
    )
    assert outs[1][0] != 0, "rank 1 should die at the SIGKILL fault"
    assert outs[0][0] != 0, "rank 0 must not report success without its peer"

    # phase 3: restart with a GROWN fleet (elastic re-plan on 2 processes)
    # + a batch NaN; everything completes
    _spawn(
        out_dir,
        "serve_campaign",
        env_extra={**base, "RUSTPDE_MP_SERVE_SLOTS": "3",
                   "RUSTPDE_FAULT": "nan@18"},
    )
    with open(os.path.join(out_dir, "result.json")) as f:
        r3 = json.load(f)
    assert r3["outcome"] == "idle"
    assert r3["queue"] == {
        "queued": 0, "running": 0, "done": n_req, "failed": 0
    }
    assert r3["replanned"] >= 1  # 2-slot checkpoint re-planned onto 3
    assert r3["restored_sched"] >= 1  # trajectories restored mid-flight
    assert r3["retries"] >= 1  # the NaN chaos actually fired

    events = read_journal(
        os.path.join(out_dir, "serve", "journal.jsonl"), on_error="skip"
    )
    names = [e.get("event") for e in events]
    assert "drain" in names and "request_requeued" in names
    assert "campaign_replanned" in names
    starts = [e for e in events if e.get("event") == "server_start"]
    assert starts[-1]["processes"] == _NPROC
    assert starts[-1]["unclean_shutdown"] is True  # phase 2's SIGKILL seen

    # isolation + topology equivalence: sampled done records vs solo
    # serial reruns (2-proc vmapped members == plain serial model)
    done_dir = os.path.join(out_dir, "serve", "queue", "done")
    sample = sorted(os.listdir(done_dir))[:3]
    for name in sample:
        with open(os.path.join(done_dir, name)) as fh:
            res = json.load(fh)["result"]
        solo = _serve_solo_nu(res)
        assert abs(res["nu"] - solo) <= 1e-9 * max(abs(solo), 1e-30)


def _gang_solo_nu(record):
    """Solo serial rerun of one served done record — EITHER grid class
    (the 34^2 gang-sharded flagship or the 18^2 vmapped co-resident
    bucket): two-level serving must stay member-, bucket- AND
    topology-isolated."""
    from rustpde_mpi_tpu import Navier2D

    req, res = record["request"], record["result"]
    m = Navier2D(
        int(req["nx"]),
        int(req["ny"]),
        float(req["ra"]),
        float(req["pr"]),
        res["dt"],
        1.0,
        req.get("bc", "rbc"),
        periodic=False,
    )
    m.init_random(res.get("amp") or 0.1, seed=res["seed"])
    m.update_n(res["steps"])
    return float(m.eval_nu())


def test_multiprocess_gang_campaign_chaos_soak(tmp_path):
    """THE two-level serving gate (PR-18 acceptance): mixed gang-sharded
    (34^2 on the carved cross-process slice) and vmapped (18^2 on the
    default remainder) traffic through three failure axes —

    1. SIGTERM drain mid-campaign: the gang campaign parks its SHARDED
       state through the two-phase continuation writer, unfinished
       requests re-enqueue, both ranks exit clean;
    2. gang-scoped SIGKILL (``kill@..:gang0member1``): one gang member
       dies mid-sharded-chunk, fate-sharing converts the survivor's
       wedged collective into typed ``GangMemberLost`` containment (a
       ``gang_member_lost`` journal row + requeue-with-progress), and
       the worker exits nonzero rather than wedging;
    3. clean restart: a NEW gang forms, reclaims the broken gang's
       requests at their parked progress, and the queue drains.

    Zero requests lost or failed, and EVERY done record — both grid
    classes, including the trajectories that crossed the gang kill —
    matches a solo serial rerun to the serve isolation tolerance."""
    from rustpde_mpi_tpu.utils.journal import read_journal

    out_dir = str(tmp_path / "mpgang")
    os.makedirs(out_dir, exist_ok=True)
    n_gang = n_vmap = 2
    base = {
        "RUSTPDE_MP_GANG_REQUESTS": str(n_gang),
        "RUSTPDE_MP_VMAP_REQUESTS": str(n_vmap),
        "RUSTPDE_MP_SERVE_SLOTS": "2",
        "RUSTPDE_SYNC_TIMEOUT_S": "60",
        "RUSTPDE_DISPATCH_TIMEOUT_S": "60",
        # the gang watchdog must convert the dead member WELL before the
        # job-wide sync budget (failure-domain isolation, not a stall)
        "RUSTPDE_GANG_SYNC_TIMEOUT_S": "30",
        "RUSTPDE_SANITIZE": "1",
    }

    # phase 1: enqueue everything (gang + vmapped + the worker's in-line
    # no_submesh rejection probe), SIGTERM drain at step 4
    _spawn(out_dir, "gang_serve", env_extra={**base, "RUSTPDE_FAULT": "kill@4"})
    with open(os.path.join(out_dir, "result.json")) as f:
        r1 = json.load(f)
    assert r1["outcome"] == "drained" and r1["requeued"] >= 1
    assert r1["gang_formed"] >= 1
    assert r1["submesh_rejected"] == 1  # typed 400 at the door, not queued
    assert r1["failed"] == 0

    # phase 2: gang member 1 SIGKILLed mid-gang-campaign — fate-sharing:
    # BOTH ranks exit nonzero, containment journals the typed loss
    outs = _spawn(
        out_dir,
        "gang_serve",
        env_extra={**base, "RUSTPDE_FAULT": "kill@6:gang0member1"},
        check=False,
    )
    assert outs[1][0] != 0, "gang member 1 should die at the SIGKILL fault"
    assert outs[0][0] != 0, "root must not report success after losing its gang"

    # phase 3: clean restart reclaims the broken gang's requests
    _spawn(out_dir, "gang_serve", env_extra=base)
    with open(os.path.join(out_dir, "result.json")) as f:
        r3 = json.load(f)
    n_all = n_gang + n_vmap
    assert r3["outcome"] == "idle"
    assert r3["queue"] == {
        "queued": 0, "running": 0, "done": n_all, "failed": 0
    }
    assert r3["gang_formed"] >= 2  # a NEW gang formed after the loss
    assert r3["gang_member_lost"] >= 1  # phase 2's typed containment row
    assert r3["restored_sched"] >= 1  # trajectories restored mid-flight

    events = read_journal(
        os.path.join(out_dir, "serve", "journal.jsonl"), on_error="skip"
    )
    names = [e.get("event") for e in events]
    assert "gang_formed" in names and "gang_member_lost" in names
    assert "drain" in names and "request_requeued" in names
    lost = [e for e in events if e.get("event") == "gang_member_lost"][-1]
    assert lost.get("gang") is not None
    requeues = [e for e in events if e.get("event") == "request_requeued"]
    assert any(e.get("gang") is not None for e in requeues)

    # loss-free + solo equivalence over EVERY done record, both grids
    done_dir = os.path.join(out_dir, "serve", "queue", "done")
    records = []
    for name in sorted(os.listdir(done_dir)):
        with open(os.path.join(done_dir, name)) as fh:
            records.append(json.load(fh))
    assert len(records) == n_all
    assert {int(r["request"]["nx"]) for r in records} == {18, 34}
    for rec in records:
        solo = _gang_solo_nu(rec)
        nu = rec["result"]["nu"]
        assert abs(nu - solo) <= 1e-9 * max(abs(solo), 1e-30)


def test_sharded_multiprocess_matches_serial_run(tmp_path):
    """A clean 2-process sharded-checkpoint run equals the serial model
    driven over the same horizon (the resilience layer must not perturb
    the physics), and its checkpoints restore across topologies."""
    from rustpde_mpi_tpu import integrate
    from rustpde_mpi_tpu.utils import checkpoint as cp

    out_dir = str(tmp_path / "mpclean")
    os.makedirs(out_dir, exist_ok=True)
    _spawn(out_dir, "sharded_run")
    with open(os.path.join(out_dir, "result.json")) as f:
        result = json.load(f)
    assert result["outcome"] == "done"

    model = _serial_34()
    integrate(model, 0.2, 0.05)
    restored = _serial_34()
    restored.read(result["checkpoint"])
    for name in model.state._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(restored.state, name)),
            np.asarray(getattr(model.state, name)),
            atol=1e-12,
            err_msg=name,
        )
