"""Split Re/Im Fourier representation tests — the periodic-on-TPU path
(VERDICT r1 missing #4).  The split base must be numerically identical to
the complex r2c base, block for block, and checkpoint files must stay
layout-compatible across the two representations."""

import numpy as np
import pytest

from rustpde_mpi_tpu import (
    Navier2D,
    Space2,
    cheb_dirichlet,
    fourier_r2c,
    fourier_r2c_split,
)

h5py = pytest.importorskip("h5py")


@pytest.fixture()
def spaces():
    n, ny = 16, 11
    return (
        Space2(fourier_r2c(n), cheb_dirichlet(ny)),
        Space2(fourier_r2c_split(n), cheb_dirichlet(ny)),
    )


def test_split_transforms_match_complex(spaces):
    sc, ss = spaces
    n, ny = sc.shape_physical
    mc = n // 2 + 1
    rng = np.random.default_rng(2)
    v = rng.standard_normal((n, ny))
    cc = np.asarray(sc.forward(v))
    cs = np.asarray(ss.forward(v))
    np.testing.assert_allclose(cs[:mc], cc.real, atol=1e-14)
    np.testing.assert_allclose(cs[mc:], cc.imag, atol=1e-14)
    np.testing.assert_allclose(
        np.asarray(ss.backward(cs)), np.asarray(sc.backward(cc)), atol=1e-13
    )


@pytest.mark.parametrize("order", [1, 2, 3])
def test_split_gradient_matches_complex(spaces, order):
    sc, ss = spaces
    n, ny = sc.shape_physical
    mc = n // 2 + 1
    rng = np.random.default_rng(3)
    v = rng.standard_normal((n, ny))
    cc = np.asarray(sc.forward(v))
    cs = np.asarray(ss.forward(v))
    gc = np.asarray(sc.gradient(cc, (order, 0), (1.0, 1.0)))
    gs = np.asarray(ss.gradient(cs, (order, 0), (1.0, 1.0)))
    np.testing.assert_allclose(gs[:mc], gc.real, atol=1e-12)
    np.testing.assert_allclose(gs[mc:], gc.imag, atol=1e-12)


def test_split_dealias_and_zero_mode(spaces):
    sc, ss = spaces
    mc = sc.shape_physical[0] // 2 + 1
    m_split = ss.dealias_mask()
    m_cplx = sc.dealias_mask()
    np.testing.assert_allclose(m_split[:mc], m_cplx)
    np.testing.assert_allclose(m_split[mc:], m_cplx)

    import jax.numpy as jnp

    arr = jnp.ones(ss.shape_spectral)
    pinned = np.asarray(ss.pin_zero_mode(arr))
    assert pinned[0, 0] == 0.0 and pinned[mc, 0] == 0.0
    assert pinned[1, 0] == 1.0


def test_split_periodic_model_matches_complex(monkeypatch):
    """Full periodic RBC model: forced split/TPU path vs the complex default
    — identical trajectory to machine precision (verified 1.8e-15/50 steps)."""

    def build():
        model = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        model.update_n(50)
        return model

    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    split_model = build()
    from rustpde_mpi_tpu.bases import BaseKind

    assert split_model.temp_space.base_kind(0) == BaseKind.FOURIER_R2C_SPLIT
    monkeypatch.delenv("RUSTPDE_FORCE_TPU_PATH")
    cplx_model = build()

    np.testing.assert_allclose(
        split_model.get_field("temp"), cplx_model.get_field("temp"), atol=1e-12
    )
    for a, b in zip(split_model.get_observables(), cplx_model.get_observables()):
        assert a == pytest.approx(b, rel=1e-10, abs=1e-12)


def test_split_checkpoint_interops_with_complex(tmp_path, monkeypatch):
    """A snapshot written by the split model restores exactly into the
    complex model and vice versa (files carry the complex convention)."""
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    split_model = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
    split_model.set_temperature(0.1, 1.0, 1.0)
    split_model.update_n(10)
    f_split = str(tmp_path / "split.h5")
    split_model.write(f_split)
    with h5py.File(f_split, "r") as h5:
        assert "temp/vhat_re" in h5 and "temp/vhat_im" in h5

    monkeypatch.delenv("RUSTPDE_FORCE_TPU_PATH")
    cplx_model = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
    cplx_model.read(f_split)
    np.testing.assert_allclose(
        cplx_model.get_field("temp"), split_model.get_field("temp"), atol=1e-13
    )
    f_cplx = str(tmp_path / "cplx.h5")
    cplx_model.write(f_cplx)

    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    split_again = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
    split_again.read(f_cplx)
    np.testing.assert_allclose(
        split_again.get_field("temp"), split_model.get_field("temp"), atol=1e-13
    )
