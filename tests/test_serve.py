"""Simulation-service tests (rustpde_mpi_tpu/serve/): durable queue +
admission control, continuous batching with per-request fault isolation,
dt-backoff retries into the typed RequestFailed terminal state, SIGTERM
graceful drain + restart-with-restore, the thin HTTP front, strict fault
spec parsing, torn-journal tolerance, and the public robustness API.

The chaos soak (≥200 requests / ≤8 slots under NaNs + a hard kill + a
drain/restart cycle, driven through subprocesses) lives in the slow tier;
the tier-1 tests here exercise every code path at small scale on the
shared 17^2 jit shapes (tests/model_builders.py).
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import pytest

from rustpde_mpi_tpu import Navier2D, RequestFailed
from rustpde_mpi_tpu.config import ServeConfig
from rustpde_mpi_tpu.serve import (
    AdmissionError,
    DurableQueue,
    RequestError,
    SimRequest,
    SimServer,
)
from rustpde_mpi_tpu.utils.faults import FaultSpecError
from rustpde_mpi_tpu.utils.journal import JournalError, JournalWriter, read_journal

h5py = pytest.importorskip("h5py")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the shared tier shapes: 17^2 rbc, dt=0.01 (and dt=0.005 on the retry
# bucket — the same shapes test_resilience's backoff tests compile)
_REQ = dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1, bc="rbc")


def _cfg(tmp_path, **kw):
    kw.setdefault("run_dir", str(tmp_path / "serve"))
    kw.setdefault("slots", 2)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("checkpoint_every_s", None)
    kw.setdefault("http_port", None)
    return ServeConfig(**kw)


def _events(run_dir):
    return read_journal(os.path.join(run_dir, "journal.jsonl"))


def _solo_nu(result):
    """Solo rerun of one done-record's trajectory: same seed/dt/steps, the
    single-model step path (no vmap, no batching)."""
    m = Navier2D(17, 17, 1e4, 1.0, result["dt"], 1.0, "rbc", periodic=False)
    m.init_random(result.get("amp") or 0.1, seed=result["seed"])
    m.update_n(result["steps"])
    return float(m.eval_nu())


def _parse_prometheus(text):
    """Strict-enough parser for the exposition format: every line must be a
    ``# HELP``/``# TYPE`` comment or ``name[{labels}] value``; returns
    ``{name: {labels_str: (value,)}}`` and asserts the format en route."""
    import re

    samples = {}
    line_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? ([0-9eE+.\-]+|NaN|[+-]Inf)$"
    )
    for line in text.splitlines():
        if not line or line.startswith("#"):
            if line:
                assert line.startswith(("# HELP ", "# TYPE ")), line
            continue
        m = line_re.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labels, value = m.groups()
        samples.setdefault(name, {})[labels or ""] = (float(value),)
    return samples


# -- requests + queue ---------------------------------------------------------


def test_request_validation_and_compat_key():
    req = SimRequest(**_REQ, seed=3)
    assert req.id and req.steps == 10
    assert req.compat_key == Navier2D(
        17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False
    ).compat_key
    for bad in (
        dict(_REQ, dt=-1.0),
        dict(_REQ, horizon=0.0),
        dict(_REQ, bc="typo"),
        dict(_REQ, nx=2),
        dict(_REQ, ra=-5.0),
    ):
        with pytest.raises(RequestError):
            SimRequest(**bad).validate()
    with pytest.raises(RequestError, match="unknown request fields"):
        SimRequest.from_dict(dict(_REQ, nonsense=1))
    # dt backoff re-buckets and records the trajectory
    retry = req.backed_off(0.5)
    assert retry.dt == pytest.approx(0.005)
    assert retry.retries == 1 and retry.dts == [0.01, 0.005]
    assert retry.compat_key != req.compat_key


def test_request_json_roundtrip_and_progress():
    req = SimRequest(**_REQ, seed=4)
    clone = SimRequest.from_json(req.to_json())
    assert clone == req
    assert clone.steps_remaining == clone.steps == 10
    # drained-campaign bookkeeping: progress reduces the remaining debt
    import dataclasses as dc

    resumed = dc.replace(clone, progress=6)
    assert resumed.steps_remaining == 4
    # backoff discards progress (a diverged trajectory is not resumable)
    assert resumed.backed_off(0.5).progress == 0


def test_admission_rejects_while_draining(tmp_path):
    srv = SimServer(_cfg(tmp_path))
    srv.request_drain()
    with pytest.raises(AdmissionError) as exc:
        srv.submit(dict(_REQ, seed=0))
    assert exc.value.reason == "draining"


def test_queue_lifecycle_recovery_and_admission(tmp_path):
    q = DurableQueue(str(tmp_path / "q"), max_queue=2)
    a = q.submit(SimRequest(**_REQ, seed=0))
    b = q.submit(SimRequest(**_REQ, seed=1))
    # bounded-queue backpressure: typed reject-with-reason, nothing written
    with pytest.raises(AdmissionError, match="queue_full") as exc:
        q.submit(SimRequest(**_REQ, seed=2))
    assert exc.value.reason == "queue_full"
    with pytest.raises(AdmissionError, match="draining"):
        q.submit(SimRequest(**_REQ, seed=2), admit_open=False)
    assert q.counts() == {"queued": 2, "running": 0, "done": 0, "failed": 0}
    # FIFO claim into running/, resolution into done/
    got = q.claim(a.compat_key)
    assert got.id == a.id
    q.complete(got, {"nu": 1.0})
    assert q.lookup(a.id)[0] == "done"
    # claim_id targets a specific queued request
    assert q.claim_id("nonexistent") is None
    assert q.claim_id(b.id).id == b.id
    # a crashed owner's running request is recovered, never lost
    assert q.recover() == [b.id]
    assert q.counts()["queued"] == 1
    assert q.lookup(b.id)[0] == "queued"
    # terminal failure record keeps the dt trajectory
    bad = q.claim()
    q.fail(bad, "diverged hard")
    state, record = q.lookup(bad.id)
    assert state == "failed" and record["error"]["dts"] == [0.01]


# -- torn journal (SIGKILL mid-append) ----------------------------------------


def test_torn_journal_tail_skipped_interior_raises(tmp_path, capsys):
    path = str(tmp_path / "journal.jsonl")
    w = JournalWriter(path)
    w.append({"event": "a"})
    w.append({"event": "b"})
    w.close()
    # a SIGKILL mid-append tears the FINAL line: skipped with a warning
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"event": "torn-mid-wri')
    records = read_journal(path)
    assert [r["event"] for r in records] == ["a", "b"]
    assert "torn trailing record" in capsys.readouterr().err
    # interior garbage is NOT a crash artifact: typed raise (or skip on ask)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write('{"event": "a"}\nGARBAGE\n{"event": "c"}\n')
    with pytest.raises(JournalError, match="interior"):
        read_journal(path)
    assert [r["event"] for r in read_journal(path, on_error="skip")] == ["a", "c"]
    # a missing journal is an empty one
    assert read_journal(str(tmp_path / "nope.jsonl")) == []


# -- strict fault-spec parsing at startup -------------------------------------


def test_malformed_fault_specs_raise_at_startup(tmp_path, monkeypatch, stepped_rbc17):
    from rustpde_mpi_tpu import ResilientRunner
    from rustpde_mpi_tpu.utils.faults import parse_shard_crash_spec

    # RUSTPDE_SHARD_CRASH is validated by the harness constructors even
    # though only the checkpoint writer consumes it: a chaos spec that
    # cannot fire must die before any stepping
    monkeypatch.setenv("RUSTPDE_SHARD_CRASH", "mid_write@4")
    with pytest.raises(FaultSpecError, match="crash point"):
        ResilientRunner(stepped_rbc17, max_time=0.1, run_dir=str(tmp_path))
    with pytest.raises(FaultSpecError):
        SimServer(_cfg(tmp_path))
    monkeypatch.delenv("RUSTPDE_SHARD_CRASH")
    monkeypatch.setenv("RUSTPDE_FAULT", "nan@notastep")
    with pytest.raises(FaultSpecError, match="bad step"):
        ResilientRunner(stepped_rbc17, max_time=0.1, run_dir=str(tmp_path))
    monkeypatch.delenv("RUSTPDE_FAULT")
    for bad in ("after_shard", "after_shard@x", "before_manifest@3:hostX"):
        with pytest.raises(FaultSpecError):
            parse_shard_crash_spec(bad)
    assert parse_shard_crash_spec(None) is None
    assert parse_shard_crash_spec("before_manifest@7") == ("before_manifest", 7, None)


def test_fault_plan_host_scope_parsing_and_locality():
    from rustpde_mpi_tpu.utils.faults import FaultPlan

    plan = FaultPlan.from_spec("kill@9:host2")
    assert (plan.kind, plan.step, plan.host) == ("kill", 9, 2)
    # single-process runtime: only host 0's scope acts here
    assert FaultPlan.from_spec("nan@3:host0").scoped_here() is True
    assert FaultPlan.from_spec("nan@3:host2").scoped_here() is False
    assert FaultPlan.from_spec("nan@3").scoped_here() is True
    assert FaultPlan.from_spec(None) is None and FaultPlan.from_spec("") is None
    for bad in ("nan@3:hostX", "nan@3:2", "kill@3:"):
        with pytest.raises(FaultSpecError):
            FaultPlan.from_spec(bad)


def test_read_journal_blank_lines_and_bad_mode(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write('{"event": "a"}\n\n   \n{"event": "b"}\n')
    assert [r["event"] for r in read_journal(path)] == ["a", "b"]
    with pytest.raises(ValueError, match="on_error"):
        read_journal(path, on_error="ignore")


def test_queue_rejects_malformed_without_writing(tmp_path):
    q = DurableQueue(str(tmp_path / "q"), max_queue=4)
    with pytest.raises(RequestError):
        q.submit(SimRequest(**dict(_REQ, dt=-1.0), seed=0))
    assert q.counts() == {"queued": 0, "running": 0, "done": 0, "failed": 0}
    assert q.lookup("nope") is None
    assert q.oldest_bucket() is None and q.claim() is None


def test_request_failed_carries_trajectory():
    err = RequestFailed("abc123", "diverged", [0.01, 0.005])
    assert err.request_id == "abc123"
    assert err.dt_trajectory == [0.01, 0.005]
    assert "abc123" in str(err) and "0.005" in str(err)


def test_campaign_dirs_stable_per_bucket(tmp_path):
    srv = SimServer(_cfg(tmp_path))
    a = SimRequest(**_REQ, seed=0)
    b = SimRequest(**dict(_REQ, dt=0.005), seed=0)
    assert srv._campaign_dir(a.compat_key) == srv._campaign_dir(a.compat_key)
    assert srv._campaign_dir(a.compat_key) != srv._campaign_dir(b.compat_key)
    assert srv.http_address is None  # http_port=None: no front bound


# -- the service: batching, isolation, retries --------------------------------


def test_serve_batch_completes_and_matches_solo(tmp_path):
    """5 requests through 2 slots: continuous refill (a finished slot is
    handed the next queued request mid-campaign), every request resolves,
    and each result matches its solo single-model run — the per-request
    isolation contract, asserted against ground truth."""
    srv = SimServer(_cfg(tmp_path, slots=2))
    ids = [srv.submit(dict(_REQ, seed=s)).id for s in range(5)]
    summary = srv.serve()
    assert summary["outcome"] == "idle"
    assert summary["completed"] == 5 and summary["failed"] == 0
    assert srv.queue.counts() == {"queued": 0, "running": 0, "done": 5, "failed": 0}
    slots_used = set()
    for i, rid in enumerate(ids):
        res = srv.result(rid)
        assert res["steps"] == 10 and res["retries"] == 0
        assert res["latency_s"] > 0
        slots_used.add(res["slot"])
        if i % 2 == 0:  # solo reruns are the slow part: sample every other
            assert res["nu"] == pytest.approx(_solo_nu(res), rel=1e-9)
    assert slots_used == {0, 1}  # both lanes actually batched work
    events = [e["event"] for e in _events(srv.cfg.run_dir)]
    assert events.count("request_done") == 5
    assert events.count("request_scheduled") == 5
    assert "campaign_end" in events and events[-1] == "server_stop"


def test_serve_divergent_member_is_isolated_and_fails_typed(tmp_path):
    """The multi-tenant nightmare scenario: one co-batched request diverges
    (absurd IC amplitude — same compat bucket, so it shares the batch).
    Its neighbours must complete bit-equal to their solo runs, and the bad
    request must land in the typed RequestFailed terminal state after its
    bounded retries."""
    srv = SimServer(_cfg(tmp_path, slots=3, request_max_retries=1))
    good = [srv.submit(dict(_REQ, seed=s)).id for s in (0, 1)]
    bad = srv.submit(dict(_REQ, seed=7, amp=1e12)).id  # diverges in-batch
    summary = srv.serve()
    assert summary["completed"] == 2 and summary["failed"] == 1
    for rid in good:
        res = srv.result(rid)
        assert res["nu"] == pytest.approx(_solo_nu(res), rel=1e-9)
    with pytest.raises(RequestFailed) as exc:
        srv.result(bad)
    assert exc.value.request_id == bad
    assert exc.value.dt_trajectory == [0.01, 0.005]  # one backoff retry
    events = [e["event"] for e in _events(srv.cfg.run_dir)]
    assert "request_retry" in events and "request_failed" in events


def test_serve_nan_fault_retries_all_members(tmp_path):
    """RUSTPDE_FAULT=nan@k poisons the whole running batch: every in-flight
    request retries at dt/2 (a fresh bucket/campaign) and completes; the
    late-queued request completes at the original dt untouched."""
    srv = SimServer(_cfg(tmp_path, slots=2), fault="nan@6")
    ids = [srv.submit(dict(_REQ, seed=s)).id for s in range(3)]
    summary = srv.serve()
    assert summary["completed"] == 3 and summary["failed"] == 0
    assert summary["retried"] == 2
    dts = sorted(srv.result(r)["dt"] for r in ids)
    assert dts == pytest.approx([0.005, 0.005, 0.01])
    for rid in ids[:2]:  # one retried + one untouched request vs solo
        res = srv.result(rid)
        assert res["nu"] == pytest.approx(_solo_nu(res), rel=1e-9)


def test_serve_admission_and_http_front(tmp_path):
    """Daemon mode behind the HTTP front: submit over POST (202 + fsynced
    durable queue), status/stats/healthz over GET, 400 on garbage, 429
    with a reason once the queue is full, drain over POST — and the drain
    resolves the in-flight request before the server returns."""
    cfg = _cfg(tmp_path, slots=2, max_queue=3, idle_exit=False, poll_s=0.05,
               http_port=0)
    srv = SimServer(cfg)
    done = {}
    thread = threading.Thread(target=lambda: done.update(srv.serve()))
    thread.start()
    try:
        for _ in range(100):
            if srv.http_address is not None:
                break
            thread.join(0.1)
        host, port = srv.http_address
        base = f"http://{host}:{port}"

        def post(path, payload):
            req = urllib.request.Request(
                base + path, data=json.dumps(payload).encode(), method="POST"
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read())

        def get(path):
            try:
                with urllib.request.urlopen(base + path, timeout=30) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read())

        def get_text(path):
            with urllib.request.urlopen(base + path, timeout=30) as resp:
                return resp.status, resp.read().decode("utf-8")

        # enriched /healthz: liveness PLUS queue depth + slot utilization
        # PLUS the fleet shape (process count / mesh topology) an operator
        # needs to see what is serving, not just that it is up
        code, health = get("/healthz")
        assert code == 200
        assert health["ok"] is True and health["draining"] is False
        assert set(health["queue"]) == {"queued", "running", "done", "failed"}
        assert {
            "running", "total", "utilization",
            "process_count", "devices", "mesh",
        } <= set(health["slots"])
        assert health["slots"]["total"] == 2
        assert health["slots"]["process_count"] == 1
        assert health["slots"]["devices"] >= 1
        assert health["slots"]["mesh"] is None  # single-controller run
        code, ack = post("/requests", dict(_REQ, seed=0))
        assert code == 202 and ack["steps"] == 10
        code, err = post("/requests", dict(_REQ, dt=-1.0))
        assert code == 400
        code, err = post("/requests", "not a dict")
        assert code == 400
        # fill the bounded queue: the 429 carries the backpressure reason
        rejected = None
        for seed in range(1, 8):
            code, body = post("/requests", dict(_REQ, seed=seed))
            if code == 429:
                rejected = body
                break
        assert rejected is not None and rejected["reason"] == "queue_full"
        # live /metrics scrape MID-SOAK: the daemon campaign is running the
        # queued requests while this GET renders the registry (the ISSUE's
        # acceptance criterion) — Prometheus-parseable, serve series present
        code, text = get_text("/metrics")
        assert code == 200
        samples = _parse_prometheus(text)
        assert "serve_queue_depth" in samples
        assert "serve_requests_admitted_total" in samples
        assert any(s[0] >= 1 for s in samples["http_requests_total"].values())
        code, status = get(f"/requests/{ack['id']}")
        assert code == 200 and status["state"] in ("queued", "running", "done")
        assert get("/requests/unknown-id")[0] == 404
        code, stats = get("/stats")
        assert code == 200 and "queue" in stats and "slots" in stats
        code, body = post("/drain", {})
        assert code == 202 and body["draining"] is True
        # concurrent submits during the drain: typed 429 with the reason
        code, body = post("/requests", dict(_REQ, seed=99))
        assert code == 429 and body["reason"] == "draining"
    finally:
        srv.request_drain()
        thread.join(timeout=300)
    assert not thread.is_alive()
    assert done["outcome"] == "drained"
    # everything admitted is either resolved or still durably queued: the
    # drain lost nothing
    counts = srv.queue.counts()
    assert counts["running"] == 0
    assert counts["done"] + counts["queued"] + counts["failed"] >= 2


def test_http_front_error_paths(tmp_path):
    """Broken HTTP frames must map to typed statuses, not tracebacks or
    hangs: non-integer / negative Content-Length -> 400, an oversized body
    -> 413 (rejected BEFORE reading), a truncated body (client hung up
    mid-send) -> 400 — and the front serves /metrics + enriched /healthz
    standalone (it only touches the scheduler's thread-safe surface)."""
    import socket

    from rustpde_mpi_tpu.serve.http_front import HttpFront

    srv = SimServer(_cfg(tmp_path))
    front = HttpFront(srv)
    front.start()
    try:
        host, port = front.address

        def raw(request: bytes) -> str:
            # send, then half-close the write side: the server sees EOF on
            # any body read it attempts (the hung-up-client shape), while
            # the read side stays open for the response
            with socket.create_connection((host, port), timeout=30) as sock:
                sock.sendall(request)
                sock.shutdown(socket.SHUT_WR)
                sock.settimeout(30)
                chunks = []
                while True:
                    data = sock.recv(65536)
                    if not data:
                        break
                    chunks.append(data)
            return b"".join(chunks).decode("utf-8", "replace")

        def post_head(extra_headers: str, body: bytes = b"") -> str:
            return raw(
                (
                    "POST /requests HTTP/1.1\r\n"
                    f"Host: {host}\r\nConnection: close\r\n"
                    f"{extra_headers}\r\n"
                ).encode()
                + body
            )

        # bad Content-Length: not an integer
        resp = post_head("Content-Length: nope\r\n")
        assert " 400 " in resp.splitlines()[0], resp.splitlines()[0]
        assert "Content-Length" in resp
        # negative Content-Length
        resp = post_head("Content-Length: -5\r\n")
        assert " 400 " in resp.splitlines()[0], resp.splitlines()[0]
        # oversized body: rejected by the declared length, nothing read
        resp = post_head(f"Content-Length: {(1 << 20) + 1}\r\n")
        assert " 413 " in resp.splitlines()[0], resp.splitlines()[0]
        # truncated body: client promises 100 bytes, sends 12, hangs up
        resp = post_head("Content-Length: 100\r\n", body=b'{"ra": 1e4, ')
        assert " 400 " in resp.splitlines()[0], resp.splitlines()[0]
        assert "truncated" in resp
        # nothing malformed was admitted
        assert srv.queue.counts()["queued"] == 0
        # standalone /metrics + /healthz (no campaign running)
        base = f"http://{host}:{port}"
        with urllib.request.urlopen(base + "/metrics", timeout=30) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            _parse_prometheus(resp.read().decode("utf-8"))
        with urllib.request.urlopen(base + "/healthz", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["ok"] is True and health["slots"]["running"] == 0
    finally:
        front.stop()


def test_serve_sigterm_drain_checkpoint_restart_resumes(tmp_path):
    """The graceful-drain contract end-to-end, in-process: kill@k fires a
    real SIGTERM mid-campaign -> the server checkpoints the slot table via
    the sharded two-phase writer, re-enqueues unfinished requests and
    returns "drained"; a SECOND server on the same run_dir re-claims the
    requests into their restored slots (mid-trajectory, not from scratch)
    and the final observables still match full solo runs."""
    mk = lambda: _cfg(tmp_path, slots=2)
    srv = SimServer(mk(), fault="kill@8")
    ids = [srv.submit(dict(_REQ, seed=s, horizon=0.2)).id for s in range(3)]
    s1 = srv.serve()
    assert s1["outcome"] == "drained"
    counts = srv.queue.counts()
    assert counts["running"] == 0 and counts["queued"] >= 2  # requeued
    events = _events(str(tmp_path / "serve"))
    requeued = [e for e in events if e["event"] == "request_requeued"]
    assert requeued and all(e["checkpoint"] for e in requeued)
    drained_ids = {e["id"] for e in requeued}

    srv2 = SimServer(mk())
    s2 = srv2.serve()
    assert s2["outcome"] == "idle"
    assert srv2.queue.counts() == {
        "queued": 0, "running": 0, "done": 3, "failed": 0
    }
    events = _events(str(tmp_path / "serve"))
    restored = {
        e["id"]: e for e in events
        if e["event"] == "request_scheduled" and e.get("restored")
    }
    # the drained requests came back mid-trajectory (steps_done > 0)
    assert set(restored) == drained_ids
    assert all(e["steps_done"] > 0 for e in restored.values())
    for rid in ids:
        res = srv2.result(rid)
        assert res["steps"] == 20
        assert res["nu"] == pytest.approx(_solo_nu(res), rel=1e-9)


def test_serve_config_and_ensemble_compat_key(tmp_path):
    from rustpde_mpi_tpu.models.ensemble import NavierEnsemble

    cfg = ServeConfig(run_dir=str(tmp_path), slots=3, max_queue=7)
    assert cfg.slots == 3 and cfg.request_dt_backoff == 0.5
    srv = SimServer(cfg)
    assert srv.queue.max_queue == 7
    # the ensemble's key IS its template model's key (one vmapped jaxpr)
    model = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    ens = NavierEnsemble.replicate(model, 2)
    assert ens.compat_key == model.compat_key
    assert ens.compat_key == SimRequest(**_REQ).compat_key
    # fresh_member_state leaves the template model's own state untouched
    before = model.state
    state = ens.fresh_member_state(seed=5, amp=0.1)
    assert model.state is before
    assert state.temp.shape == model.state.temp.shape


def test_queue_fifo_order_survives_reopen(tmp_path):
    q = DurableQueue(str(tmp_path / "q"), max_queue=8)
    ids = [q.submit(SimRequest(**_REQ, seed=s)).id for s in range(3)]
    # a NEW queue object over the same directory (process restart) claims
    # in the original submit order — ordering is on-disk, not in-memory
    q2 = DurableQueue(str(tmp_path / "q"), max_queue=8)
    assert [q2.claim().id for _ in range(3)] == ids
    assert q2.claim() is None


def test_journal_writer_reopens_after_close(tmp_path):
    path = str(tmp_path / "j.jsonl")
    w = JournalWriter(path)
    w.append({"event": "a"})
    w.close()
    w.append({"event": "b"})  # lazily reopens
    w.close()
    assert [r["event"] for r in read_journal(path)] == ["a", "b"]


def test_runner_embedding_surface(tmp_path, stepped_rbc17):
    """The session()/advance()/checkpoint_now()/on_boundary() surface the
    serve scheduler embeds: services armed without run()'s driver loop,
    drain flag via request_drain, manual checkpoints on demand."""
    from rustpde_mpi_tpu import ResilientRunner
    from rustpde_mpi_tpu.utils import checkpoint as cp

    runner = ResilientRunner(
        stepped_rbc17,
        max_time=float("inf"),
        run_dir=str(tmp_path / "run"),
        checkpoint_every_s=None,
    )
    with runner.session(install_signals=False, resume=False):
        assert runner.resumed is False
        before = runner.step
        runner.advance(3)
        assert runner.step == before + 3
        assert runner.on_boundary() is False  # no drain requested yet
        path = runner.checkpoint_now("drain")
        assert path and cp.verify_snapshot(path)
        assert int(cp.read_attrs(path)["step"]) == runner.step
        runner.request_drain()
        assert runner.drain_requested() is True
        assert runner.on_boundary() is True  # the embedder's stop signal


def test_drain_restart_grow_replans_and_continues(tmp_path):
    """Elastic fleet GROW across a drain/restart cycle: the restart builds
    the fleet at the checkpoint's slot count, restores every drained
    trajectory MID-FLIGHT, then re-plans onto the larger configured fleet
    — kept requests continue from their checkpointed step counters in the
    new lanes, grown lanes refill from the queue, and the journal records
    a ``campaign_replanned`` event with old/new K."""
    srv = SimServer(_cfg(tmp_path, slots=2), fault="kill@8")
    ids = [srv.submit(dict(_REQ, seed=s, horizon=0.2)).id for s in range(4)]
    assert srv.serve()["outcome"] == "drained"

    srv2 = SimServer(_cfg(tmp_path, slots=3))  # ops grew the fleet
    s2 = srv2.serve()
    assert s2["outcome"] == "idle"
    assert srv2.queue.counts()["done"] == 4 and s2["failed"] == 0
    assert s2["replans"] == 1
    events = _events(str(tmp_path / "serve"))
    replans = [e for e in events if e["event"] == "campaign_replanned"]
    assert len(replans) == 1
    assert replans[0]["old_slots"] == 2 and replans[0]["new_slots"] == 3
    assert replans[0]["kept"] == 2 and replans[0]["parked"] == 0
    # NOT the degrade path: the old checkpoint restored, nothing was swept
    assert all(e["event"] != "campaign_restore_failed" for e in events)
    # the kept requests came back mid-trajectory (steps_done > 0)
    restored = [
        e for e in events
        if e["event"] == "request_scheduled" and e.get("restored")
    ]
    assert len(restored) == 2
    assert all(e["steps_done"] > 0 for e in restored)
    for rid in ids:
        res = srv2.result(rid)
        assert res["steps"] == 20
        assert res["nu"] == pytest.approx(_solo_nu(res), rel=1e-9)
    # fleet-shape telemetry: the re-plan left its marks on the live gauges
    from rustpde_mpi_tpu import telemetry

    snap = telemetry.snapshot()
    assert "serve_fleet_size" in snap and "serve_replans_total" in snap


def test_drain_restart_shrink_replans_parks_and_continues(tmp_path):
    """Elastic fleet SHRINK: 3 drained mid-flight trajectories restart on
    a 2-slot fleet.  Two move into the new lanes; the surplus one is
    PARKED (member state held) and re-enqueued at its checkpointed
    progress — when a lane frees it continues MID-FLIGHT (scheduled with
    ``parked: true`` and a nonzero base), and its final result still
    matches the full solo trajectory."""
    srv = SimServer(_cfg(tmp_path, slots=3), fault="kill@8")
    ids = [srv.submit(dict(_REQ, seed=s, horizon=0.2)).id for s in range(3)]
    assert srv.serve()["outcome"] == "drained"

    srv2 = SimServer(_cfg(tmp_path, slots=2))  # ops shrank the fleet
    s2 = srv2.serve()
    assert s2["outcome"] == "idle"
    assert srv2.queue.counts()["done"] == 3 and s2["failed"] == 0
    events = _events(str(tmp_path / "serve"))
    replans = [e for e in events if e["event"] == "campaign_replanned"]
    assert len(replans) == 1
    assert replans[0]["old_slots"] == 3 and replans[0]["new_slots"] == 2
    assert replans[0]["kept"] == 2 and replans[0]["parked"] == 1
    # the surplus request was requeued parked at its checkpointed progress
    parked_requeues = [
        e for e in events
        if e["event"] == "request_requeued" and e.get("parked")
    ]
    assert len(parked_requeues) == 1 and parked_requeues[0]["progress"] > 0
    # ... and later CONTINUED mid-flight in a freed lane, not restarted
    parked_scheduled = [
        e for e in events
        if e["event"] == "request_scheduled" and e.get("parked")
    ]
    assert len(parked_scheduled) == 1
    assert parked_scheduled[0]["id"] == parked_requeues[0]["id"]
    assert parked_scheduled[0]["base"] == parked_requeues[0]["progress"]
    for rid in ids:
        res = srv2.result(rid)
        assert res["steps"] == 20
        assert res["nu"] == pytest.approx(_solo_nu(res), rel=1e-9)


def test_serve_governed_bucket_dt_rebucket(tmp_path, monkeypatch):
    """The governed-campaign gate, in-process: a velocity spike hits the
    running bucket mid-campaign.  With ``cfg.stability`` armed the
    on-device CFL sentinels catch it while every member is still FINITE,
    the chunk rolls back in memory, and the pinned requests are re-bucketed
    at a lower rung of the per-bucket dt ladder WITH their state (journal
    ``bucket_dt_adjust``) — the campaign finishes with ZERO reactive
    retries and zero failures, where the ungoverned path would NaN and
    burn the per-request retry budget."""
    from rustpde_mpi_tpu.config import StabilityConfig

    # size the spike well past the CFL ceiling: the base flow at this
    # config runs at CFL ~0.035 and the spike partially decays through the
    # step's velocity recomputation, so x500 lands the chunk at CFL ~3.4 —
    # over the 1.0 ceiling with margin, under the NaN horizon
    monkeypatch.setenv("RUSTPDE_SPIKE_FACTOR", "500")
    srv = SimServer(
        _cfg(tmp_path, slots=2, stability=StabilityConfig(ladder_ratio=4.0)),
        fault="spike@6",
    )
    ids = [srv.submit(dict(_REQ, seed=s)).id for s in range(2)]
    summary = srv.serve()
    assert summary["outcome"] == "idle"
    assert summary["completed"] == 2 and summary["failed"] == 0
    assert summary["retried"] == 0  # zero REACTIVE retries: caught pre-NaN
    assert summary["bucket_dt_adjusts"] >= 2  # both pinned members moved
    events = _events(srv.cfg.run_dir)
    names = [e["event"] for e in events]
    assert "bucket_dt_adjust" in names
    assert "request_retry" not in names  # the reactive path never fired
    adjusts = [e for e in events if e["event"] == "bucket_dt_adjust"]
    assert all(e["dt"] < e["prev_dt"] for e in adjusts)
    assert all(e["rung"] < 0 and e["cfl"] > 0 for e in adjusts)
    # the re-bucketed requests CONTINUED (parked state, nonzero base) and
    # completed at the reduced dt with MORE total steps, finite results
    import math

    for rid in ids:
        res = srv.result(rid)
        assert res["dt"] < 0.01 and res["steps"] > 10
        assert res["retries"] == 0
        assert math.isfinite(res["nu"])
    sched = [
        e for e in events
        if e["event"] == "request_scheduled" and e.get("parked")
    ]
    assert len(sched) >= 2 and all(e["base"] > 0 for e in sched)
    from rustpde_mpi_tpu import telemetry

    assert "serve_bucket_dt_rung" in telemetry.snapshot()


def test_serve_governed_stable_dt_bit_identical(tmp_path):
    """At a stable dt the governed campaign must be BIT-identical to the
    ungoverned one: the sentinels only reduce arrays the step already
    materializes, and with no ceiling trip the scheduler takes the exact
    same claim/chunk/settle sequence."""
    from rustpde_mpi_tpu.config import StabilityConfig

    results = {}
    for tag, stab in (("plain", None), ("governed", StabilityConfig())):
        srv = SimServer(
            _cfg(tmp_path, run_dir=str(tmp_path / tag), slots=2, stability=stab)
        )
        ids = [srv.submit(dict(_REQ, seed=s)).id for s in range(3)]
        summary = srv.serve()
        assert summary["completed"] == 3 and summary["failed"] == 0
        results[tag] = [srv.result(r) for r in ids]
    for plain, governed in zip(results["plain"], results["governed"]):
        assert plain["steps"] == governed["steps"]
        assert plain["nu"] == governed["nu"]  # bit-equal, not approx
        assert plain["nuvol"] == governed["nuvol"]
        assert plain["re"] == governed["re"]


def _solo_lnse_energy(result):
    """Solo rerun of one lnse done-record's trajectory through the
    workloads registry — the mixed-campaign isolation ground truth."""
    from rustpde_mpi_tpu.workloads import build_model

    m = build_model("lnse", 17, 17, 1e4, 1.0, result["dt"], 1.0, "rbc", False)
    m.init_random(result.get("amp") or 0.1, seed=result["seed"])
    m.update_n(result["steps"])
    return float(m.get_observables()[0])


def test_serve_mixed_model_campaign(tmp_path):
    """The multi-model serving contract end-to-end: DNS and lnse requests
    through ONE server — the kind-prefixed compat key buckets them into
    separate registry-built campaigns, every request resolves (zero lost),
    results carry each model's own observable vocabulary, and per-request
    isolation holds against solo ground truth for BOTH kinds."""
    srv = SimServer(_cfg(tmp_path, slots=2))
    dns_ids = [srv.submit(dict(_REQ, seed=s)).id for s in range(2)]
    lnse_ids = [
        srv.submit(dict(_REQ, model="lnse", seed=s, amp=1e-3)).id
        for s in range(2)
    ]
    summary = srv.serve()
    assert summary["completed"] == 4 and summary["failed"] == 0
    assert srv.queue.counts() == {"queued": 0, "running": 0, "done": 4, "failed": 0}
    for rid in dns_ids:
        res = srv.result(rid)
        assert res["model"] == "dns" and "nu" in res
        assert res["nu"] == pytest.approx(_solo_nu(res), rel=1e-9)
    for rid in lnse_ids:
        res = srv.result(rid)
        assert res["model"] == "lnse" and "energy" in res and "nu" not in res
        assert res["energy"] == pytest.approx(_solo_lnse_energy(res), rel=1e-9)
    # two separate campaigns ran (one per model-kind bucket)
    events = _events(srv.cfg.run_dir)
    keys = [tuple(e["key"]) for e in events if e["event"] == "campaign_start"]
    assert {k[0] for k in keys} == {"dns", "lnse"}
    # malformed model kinds die at admission, before any compile
    with pytest.raises(RequestError, match="unknown model kind"):
        srv.submit(dict(_REQ, model="nope"))
    with pytest.raises(RequestError, match="DNS axis"):
        srv.submit(dict(_REQ, model="lnse", scenario={"coriolis": 1.0}))
    # bad scenario VALUES die at admission too — compat_key is evaluated
    # after admission, so a bad-typed value admitted here would be a
    # durable poison pill crashing every later serve() pass
    with pytest.raises(RequestError, match="bad scenario values"):
        srv.submit(dict(_REQ, scenario={"coriolis": "fast"}))
    with pytest.raises(RequestError, match="bad scenario values"):
        srv.submit(
            dict(_REQ, scenario={"passive_scalar": True, "scalar_kappa": 0.0})
        )
    assert srv.queue.counts()["queued"] == 0  # nothing poisonous persisted


def test_serve_passive_scalar_surfaces_sherwood(tmp_path):
    """The scalar observable vocabulary rides the serve path end-to-end: a
    passive-scalar request's done record carries ``sherwood`` next to the
    conventional four (streamed through the same observable futures), and
    a plain DNS record does not."""
    srv = SimServer(_cfg(tmp_path, slots=2))
    scal = srv.submit(
        dict(_REQ, seed=0, scenario={"passive_scalar": True})
    ).id
    plain = srv.submit(dict(_REQ, seed=0)).id
    summary = srv.serve()
    assert summary["completed"] == 2 and summary["failed"] == 0
    res = srv.result(scal)
    assert res["steps"] == 10
    import math

    assert math.isfinite(res["sherwood"])
    assert {"nu", "nuvol", "re", "div", "sherwood"} <= set(res)
    assert "sherwood" not in srv.result(plain)


def test_serve_bucket_fairness_no_starvation(tmp_path):
    """The fairness regression (ROADMAP-flagged): two buckets with skewed
    arrivals — 6 hot-bucket requests queued ahead of 2 cold-bucket ones.
    With round-robin bucket selection + the claim quantum, the cold bucket
    is served after one quantum of the hot one instead of waiting for its
    whole backlog: every cold request completes before the hot tail is even
    scheduled."""
    srv = SimServer(_cfg(tmp_path, slots=2, bucket_quantum=2))
    hot = [srv.submit(dict(_REQ, seed=s)).id for s in range(6)]
    cold = [srv.submit(dict(_REQ, dt=0.005, seed=s)).id for s in range(2)]
    summary = srv.serve()
    assert summary["completed"] == 8 and summary["failed"] == 0

    events = _events(srv.cfg.run_dir)
    order = [
        (e["event"], e["id"]) for e in events
        if e["event"] in ("request_scheduled", "request_done")
    ]
    last_cold_done = max(
        i for i, (ev, rid) in enumerate(order)
        if ev == "request_done" and rid in cold
    )
    hot_sched = [
        i for i, (ev, rid) in enumerate(order)
        if ev == "request_scheduled" and rid in hot
    ]
    # the hot tail (claims 5..6) was scheduled only AFTER the cold bucket
    # fully completed — the quantum actually preempted the hot campaign
    assert sum(1 for i in hot_sched if i < last_cold_done) <= 4
    assert sum(1 for i in hot_sched if i > last_cold_done) >= 2
    names = [e["event"] for e in events]
    assert "bucket_quantum" in names  # the cap fired, not a coincidence


def test_public_robustness_api_exports():
    """The README-documented robustness surface must be importable from the
    package root (satellite: pin the API)."""
    import rustpde_mpi_tpu as rp

    for name in (
        "ResilientRunner",
        "CheckpointError",
        "DivergenceError",
        "DispatchHang",
        "RequestFailed",
        "AdmissionError",
        "FaultSpecError",
        "SimServer",
        "SimRequest",
    ):
        assert hasattr(rp, name), name
    # the typed failure surface subclasses what callers already catch
    assert issubclass(rp.FaultSpecError, ValueError)
    assert issubclass(rp.RequestFailed, RuntimeError)
    assert issubclass(rp.CheckpointError, RuntimeError)


# -- the chaos soak (slow tier) ----------------------------------------------


def _summary_of(stdout):
    """The summary JSON line (restore prints and per-request lines ride the
    same stdout)."""
    for line in stdout.splitlines():
        if line.startswith('{"outcome"'):
            return json.loads(line)
    raise AssertionError(f"no summary line in: {stdout[-2000:]}")


def _run_soak_phase(run_dir, extra, timeout=900):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        RUSTPDE_X64="1",
    )
    env.pop("RUSTPDE_FAULT", None)
    return subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "examples", "navier_rbc_serve.py"),
            "--nx", "17", "--ny", "17", "--ra", "1e4", "--dt", "0.01",
            "--horizon", "0.06", "--horizon-jitter", "8",
            "--slots", "8",
            "--max-queue", "512",
            "--run-dir", run_dir,
            "--ckpt-every-s", "5",
            *extra,
        ],
        cwd=_REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


@pytest.mark.slow
def test_serve_chaos_soak(tmp_path):
    """The soak gate: >=200 queued requests complete through 8 ensemble
    slots while the service is SIGTERM-drained mid-soak (kill@ fault),
    hard-killed (SIGKILL via the host-scoped kill fault), and NaN-poisoned
    (nan@ fault) across three process incarnations — zero requests lost or
    terminally failed, and a sample of results matches solo runs within
    the respawn-equivalence tolerance."""
    run_dir = str(tmp_path / "soak")
    n_req = int(os.environ.get("RUSTPDE_SERVE_SOAK_REQUESTS", "200"))

    # the workload is ~(n_req * ~9.5 steps) / 8 slots ≈ 1.2*n_req global
    # chunk steps.  Each later phase RESTORES the previous phase's
    # checkpoint, so its step counter resumes near the previous fault
    # point — the fault steps are spaced so every phase deterministically
    # reaches its trigger with the remaining workload to spare
    drain_at = max(16, n_req // 4)
    kill_at = drain_at + max(16, n_req // 4)
    nan_at = kill_at + max(16, n_req // 4)
    # phase 1: enqueue everything, serve until the kill@ SIGTERM drains
    p1 = _run_soak_phase(
        run_dir, ["--requests", str(n_req), "--fault", f"kill@{drain_at}"]
    )
    assert p1.returncode == 0, p1.stderr[-3000:]
    assert _summary_of(p1.stdout)["outcome"] == "drained"

    # phase 2: resume, then die HARD (host-scoped kill = SIGKILL, no drain)
    p2 = _run_soak_phase(run_dir, ["--fault", f"kill@{kill_at}:host0"])
    assert p2.returncode != 0  # SIGKILL: no clean exit, no summary
    assert "outcome" not in p2.stdout

    # phase 3: clean restart + NaN chaos mid-soak; drains everything
    p3 = _run_soak_phase(run_dir, ["--fault", f"nan@{nan_at}"], timeout=1800)
    assert p3.returncode == 0, p3.stderr[-3000:]
    assert _summary_of(p3.stdout)["outcome"] == "idle"

    # zero lost: every admitted request is terminally resolved, none failed
    q = DurableQueue(os.path.join(run_dir, "queue"), max_queue=512)
    counts = q.counts()
    assert counts == {"queued": 0, "running": 0, "done": n_req, "failed": 0}

    events = read_journal(os.path.join(run_dir, "journal.jsonl"))
    names = [e["event"] for e in events]
    assert "drain" in names  # phase-1 SIGTERM drain
    assert "request_requeued" in names  # in-flight work preserved at drain
    # later incarnations restored drained/killed slots MID-TRAJECTORY from
    # the sharded slot-table checkpoint (not from scratch)
    restored = [
        e for e in events
        if e.get("event") == "request_scheduled" and e.get("restored")
    ]
    assert restored and any(e.get("steps_done", 0) > 0 for e in restored)
    # phase 3 detected phase 2's SIGKILL as an unclean shutdown and
    # recovered its running requests
    starts = [e for e in events if e.get("event") == "server_start"]
    assert starts[-1]["unclean_shutdown"] is True
    assert any(e.get("recovered") for e in starts)
    assert "request_retry" in names  # the NaN chaos actually fired

    # isolation spot-check: sample done records against solo ground truth
    done_dir = os.path.join(run_dir, "queue", "done")
    sample = sorted(os.listdir(done_dir))[:: max(1, n_req // 5)][:5]
    for name in sample:
        with open(os.path.join(done_dir, name)) as fh:
            res = json.load(fh)["result"]
        assert res["nu"] == pytest.approx(_solo_nu(res), rel=1e-9)


# -- request tracing end-to-end (ISSUE 13 tentpole) ----------------------------


def test_trace_context_survives_drain_restart_and_rebucket(tmp_path, monkeypatch):
    """The acceptance gate: admission -> SIGTERM drain -> restart ->
    re-claim -> proactive re-bucket at a lower dt rung -> done yields ONE
    trace_id across both incarnations' journal rows, and the assembled
    Perfetto timeline (the /requests/<id>/trace payload) reconstructs the
    whole lifecycle on one ordered timeline."""
    from rustpde_mpi_tpu.config import StabilityConfig

    monkeypatch.setenv("RUSTPDE_SPIKE_FACTOR", "500")
    mk = lambda fault: SimServer(
        _cfg(tmp_path, slots=2, stability=StabilityConfig(ladder_ratio=4.0)),
        fault=fault,
    )
    # incarnation 1: admitted, scheduled, SIGTERM-drained mid-campaign
    srv = mk("kill@8")
    req = srv.submit(dict(_REQ, seed=0, horizon=0.2))
    rid, tid = req.id, req.trace_id
    assert tid and len(tid) == 16
    assert srv.serve()["outcome"] == "drained"
    # incarnation 2: re-claims mid-trajectory, a velocity spike trips the
    # CFL sentinel -> bucket_dt_adjust re-buckets at dt/4, completes
    srv2 = mk("spike@14")
    s2 = srv2.serve()
    assert s2["outcome"] == "idle"
    assert s2["completed"] == 1 and s2["failed"] == 0
    assert s2["bucket_dt_adjusts"] >= 1

    events = _events(str(tmp_path / "serve"))
    mine = [e for e in events if e.get("id") == rid]
    names = [e["event"] for e in mine]
    for expected in (
        "request_admitted",
        "request_scheduled",
        "request_requeued",  # the drain
        "bucket_dt_adjust",  # the re-bucket
        "request_done",
    ):
        assert expected in names, (expected, names)
    # ONE trace id across every lifecycle row of both incarnations
    tids = {e["trace_id"] for e in mine if e.get("trace_id")}
    assert tids == {tid}
    # the restart re-claimed the drained slot mid-trajectory
    assert any(
        e.get("restored") for e in mine if e["event"] == "request_scheduled"
    )
    # every row carries the absolute stamp assembly orders by
    assert all(isinstance(e.get("t"), float) for e in mine)

    # the assembled timeline: one trace, both incarnations, ordered
    trace = srv2.request_trace(rid)
    assert trace is not None
    other = trace["otherData"]
    assert other["trace_id"] == tid and other["request_id"] == rid
    assert other["incarnations"] == 2
    tnames = [e["name"] for e in trace["traceEvents"]]
    assert "request_admitted" in tnames and "request_done" in tnames
    assert "bucket_dt_adjust" in tnames
    assert tnames.count("chunk") >= 2  # device work in BOTH incarnations
    assert "queued" in tnames and "running" in tnames  # derived phases
    assert all(
        e["args"]["trace_id"] == tid for e in trace["traceEvents"]
    )
    ts = [e["ts"] for e in trace["traceEvents"]]
    assert ts == sorted(ts) and ts[0] == 0.0
    # the per-campaign Perfetto files the assembly read actually landed
    # (root-side write at campaign close/drain) — across TWO buckets (the
    # original dt and the re-bucketed rung)
    import glob

    tfiles = glob.glob(
        os.path.join(str(tmp_path / "serve"), "campaigns", "*", "trace_*.json")
    )
    assert len(tfiles) >= 2
    assert any(e["event"] == "campaign_trace" for e in events)
    # flight dumps of the drain are sequenced and attributable
    frs = [e for e in events if e.get("event") == "flight_record"]
    assert frs and all("seq" in e for e in frs)


def test_http_trace_and_profile_endpoints(tmp_path, monkeypatch):
    """GET /requests/<id>/trace serves the assembled timeline, POST
    /profile drives the bounded single-flight profiler capture, and the
    202 admission ack carries the trace id clients correlate on."""
    from rustpde_mpi_tpu.serve.http_front import HttpFront
    from rustpde_mpi_tpu.telemetry import compile_log

    srv = SimServer(_cfg(tmp_path, slots=2))
    req = srv.submit(dict(_REQ, seed=0))
    assert srv.serve()["completed"] == 1
    # keep the profiler itself out of the test: injected no-op trace fns
    monkeypatch.setattr(
        compile_log,
        "CAPTURE",
        compile_log.ProfilerCapture(
            start_fn=lambda d: None, stop_fn=lambda: None
        ),
    )
    front = HttpFront(srv)
    front.start()
    try:
        host, port = front.address
        base = f"http://{host}:{port}"

        def get(path):
            try:
                with urllib.request.urlopen(base + path, timeout=30) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read())

        def post(path, payload=None):
            data = json.dumps(payload or {}).encode()
            r = urllib.request.Request(base + path, data=data, method="POST")
            try:
                with urllib.request.urlopen(r, timeout=30) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read())

        code, trace = get(f"/requests/{req.id}/trace")
        assert code == 200
        assert trace["otherData"]["trace_id"] == req.trace_id
        names = [e["name"] for e in trace["traceEvents"]]
        assert "request_admitted" in names and "chunk" in names
        assert get("/requests/unknown-id/trace")[0] == 404
        # profile endpoint: bad args typed, good args 202, concurrent 409
        assert post("/profile?seconds=nope")[0] == 400
        assert post("/profile?seconds=-1")[0] == 400
        code, status = post("/profile?seconds=2")
        assert code == 202 and status["started"] is True
        code, refusal = post("/profile?seconds=1")
        assert code == 409 and "already running" in refusal["error"]
        # the admission ack names the trace id
        code, ack = post("/requests", dict(_REQ, seed=5))
        assert code == 202 and len(ack["trace_id"]) == 16
    finally:
        front.stop()
    # the capture was journaled (observability events ride the journal too)
    events = [e["event"] for e in _events(srv.cfg.run_dir)]
    assert "profile_capture" in events


def test_compile_attribution_rides_serve_journal(tmp_path):
    """Every campaign build journals phase-stamped compile_build rows
    (key-tagged, wall time, recompile flag): one "build" row for the
    registry's model construction plus one "entry_points" row for the
    campaign-level remainder — summing to the bucket's true cold cost —
    and the first committed chunk a first_chunk row — the cold-start
    item's baseline numbers, durably recorded."""
    srv = SimServer(_cfg(tmp_path, slots=2))
    # unique ra => compat keys no other test in this process has built,
    # so the recompile=False assertion holds under any suite ordering
    # (the build counter is process-global by design)
    srv.submit(dict(_REQ, ra=1.2e4, seed=0))
    srv.submit(dict(_REQ, ra=1.2e4, dt=0.005, seed=1))  # second bucket
    assert srv.serve()["completed"] == 2
    events = _events(srv.cfg.run_dir)
    builds = [e for e in events if e["event"] == "compile_build"]
    assert len(builds) == 4
    assert all(len(e["key_tag"]) == 12 for e in builds)
    by_phase = {"build": [], "entry_points": []}
    for e in builds:
        by_phase[e["phase"]].append(e)
    assert len(by_phase["build"]) == 2 and len(by_phase["entry_points"]) == 2
    assert all(e["wall_s"] > 0 for e in by_phase["build"])
    assert all(e["wall_s"] >= 0 for e in by_phase["entry_points"])
    # no phase recompiles on first builds, and the rows carry the campaign k
    assert all(e["recompile"] is False and e["k"] == 2 for e in builds)
    firsts = [e for e in events if e["event"] == "first_chunk"]
    assert len(firsts) == 2
    assert all(e["wall_s"] > 0 for e in firsts)
    # the done records carry the HA gate metric
    done = [e for e in events if e["event"] == "request_done"]
    assert all(e["first_observable_s"] > 0 for e in done)
