"""Collective-sequence sanitizer (parallel/sanitizer.py).

Tier-1: single-process recording/ring/digest/injection-parsing semantics +
the disabled-mode ~free contract.  Slow tier: the 2-process desync
injection — a deliberately skipped broadcast on host 1 must raise a typed
CollectiveDesyncError naming the divergent call site on BOTH hosts within
one verification cadence (the PR-10 drain-check bug, diagnosed at runtime
instead of wedging the fleet).
"""

import json
import os

import numpy as np
import pytest

from rustpde_mpi_tpu import CollectiveDesyncError
from rustpde_mpi_tpu.parallel import multihost, sanitizer


@pytest.fixture()
def armed(monkeypatch):
    monkeypatch.setenv("RUSTPDE_SANITIZE", "1")
    monkeypatch.delenv("RUSTPDE_SANITIZE_INJECT", raising=False)
    sanitizer.reset()
    yield
    monkeypatch.setenv("RUSTPDE_SANITIZE", "0")
    sanitizer.reset()


def test_disabled_records_nothing():
    sanitizer.reset()
    assert not sanitizer.enabled()
    before = sanitizer.stats()
    multihost.sync_hosts("san-off")
    multihost.broadcast(np.int32(3))
    multihost.root_decides(True)
    after = sanitizer.stats()
    assert after["records"] == before["records"] == 0
    assert after["seq"] == 0


def test_recording_ring_and_sites(armed):
    multihost.sync_hosts("san-tag")
    multihost.broadcast(np.float64([1.0, 2.0]))
    multihost.root_decides(False)
    multihost.allgather_host(np.int64(7))
    st = sanitizer.stats()
    assert st["enabled"] and st["records"] == 4 and st["seq"] == 4
    ring = list(sanitizer._STATE.ring)
    kinds = [e["kind"] for e in ring]
    assert kinds == ["sync", "broadcast", "root_decides", "allgather"]
    assert ring[0]["tag"] == "san-tag"
    # payload-schema digests carry dtype+shape, not values
    assert ring[1]["schema"] == "float64[2]"
    assert ring[3]["schema"] == "int64[]"
    # call sites resolve OUTSIDE multihost.py, to this test file
    for e in ring:
        assert "test_sanitizer.py" in e["site"], e


def test_ring_is_bounded_and_hash_covers_history(armed):
    cap = sanitizer._STATE.ring.maxlen
    for _ in range(cap + 5):
        multihost.root_decides(True)
    assert len(sanitizer._STATE.ring) == cap
    assert sanitizer.stats()["seq"] == cap + 5  # running hash keeps counting


def test_single_process_verify_is_noop(armed):
    for _ in range(3):
        multihost.broadcast(np.int32(1))
    sanitizer.verify()  # must not raise nor exchange anything
    assert sanitizer.stats()["desyncs"] == 0


def test_values_unchanged_when_armed(armed):
    # host-side only: the sanitizer must never alter what the collectives
    # return (bit-identity of full runs is gated in bench.py governor129)
    assert int(multihost.broadcast(np.int32(41))) == 41
    assert multihost.root_decides(True) is True
    assert multihost.root_decides(False) is False
    out = multihost.allgather_host(np.float64(2.5))
    assert out.shape == (1,) and float(out[0]) == 2.5


def test_np_schema():
    assert sanitizer.np_schema(np.zeros((2, 3), np.uint8)) == "uint8[2, 3]"
    assert sanitizer.np_schema(3) == "int64[]"


def test_inject_spec_strict_parse():
    good = sanitizer._InjectPlan.from_spec("skip_broadcast@5:host1")
    assert good.call == 5 and good.host == 1
    assert sanitizer._InjectPlan.from_spec(None) is None
    for bad in ("skip@5", "skip_broadcast@x", "skip_broadcast@5:h1", "skip_broadcast5"):
        with pytest.raises(ValueError):
            sanitizer._InjectPlan.from_spec(bad)


def test_desync_error_shape():
    exc = CollectiveDesyncError("msg", seq=7, sites={0: {"site": "a.py:1"}}, site="a.py:1")
    assert exc.seq == 7 and exc.site == "a.py:1" and 0 in exc.sites
    assert isinstance(exc, RuntimeError)


def test_env_cadence_and_capacity(monkeypatch):
    monkeypatch.setenv("RUSTPDE_SANITIZE", "1")
    monkeypatch.setenv("RUSTPDE_SANITIZE_CADENCE", "5")
    monkeypatch.setenv("RUSTPDE_SANITIZE_RING", "16")
    sanitizer.reset()
    assert sanitizer.stats()["cadence"] == 5
    assert sanitizer._STATE.ring.maxlen == 16
    monkeypatch.setenv("RUSTPDE_SANITIZE", "0")
    monkeypatch.delenv("RUSTPDE_SANITIZE_CADENCE")
    monkeypatch.delenv("RUSTPDE_SANITIZE_RING")
    sanitizer.reset()


# -- 2-process desync injection (slow tier) -----------------------------------


@pytest.mark.slow
def test_mp_desync_injection_raises_on_both_hosts(tmp_path):
    """Host 1 silently skips one broadcast (the PR-10 drain-check shape):
    both ranks must raise CollectiveDesyncError naming the divergent call
    site within ONE verification cadence — and a clean run under the same
    arming must not trip."""
    from mp_harness import spawn_cluster

    env = {
        "RUSTPDE_SANITIZE": "1",
        "RUSTPDE_SANITIZE_CADENCE": "8",
        "RUSTPDE_SYNC_TIMEOUT_S": "60",
    }
    # clean leg: armed, no injection, no trips
    clean_dir = str(tmp_path / "clean")
    os.makedirs(clean_dir)
    outs = spawn_cluster(clean_dir, mode="sanitize_desync", timeout=300, env_extra=env)
    assert outs is not None, "clean sanitize spawn timed out"
    for rank in (0, 1):
        with open(os.path.join(clean_dir, f"sanitize_rank{rank}.json")) as fh:
            r = json.load(fh)
        assert r["raised"] is None, r
        assert r["stats"]["verifies"] >= 1 and r["stats"]["desyncs"] == 0

    # injected leg: host1 skips its 5th broadcast
    inj_dir = str(tmp_path / "inject")
    os.makedirs(inj_dir)
    outs = spawn_cluster(
        inj_dir,
        mode="sanitize_desync",
        timeout=300,
        env_extra={**env, "RUSTPDE_SANITIZE_INJECT": "skip_broadcast@5:host1"},
    )
    assert outs is not None, "injected sanitize spawn timed out"
    for rank in (0, 1):
        with open(os.path.join(inj_dir, f"sanitize_rank{rank}.json")) as fh:
            r = json.load(fh)
        assert r["raised"] == "CollectiveDesyncError", (rank, r)
        # the first divergent call site is named, and it is the worker's
        # root_decides loop
        assert r["site"] and "mp_worker.py" in r["site"], r
        assert r["seq"] is not None and r["seq"] > 0
        # detected at the FIRST verification after the skip (cadence 8
        # executed collectives; the skip lands at call 5)
        assert r["stats"]["verifies"] == 1, r
        assert r["stats"]["desyncs"] == 1, r
