"""Pallas fused convection chain: interpreter-mode parity suite.

Mirrors tests/test_pallas_banded.py's role: the kernel runs in Pallas
interpreter mode on the CPU CI mesh (natively on an attached TPU), so tier-1
exercises the fused chain on every layout without a chip.  Documented
tolerances: the kernel computes the same linear chain with one reassociation
(dense GEMMs vs folded half-GEMMs / FFT paths), so parity is fp-epsilon in
f64 and ~1e-5 relative in f32 / f64-hybrid.

Also covers the stable ``Base.axis_operator`` accessor (the fold-structure
source of truth the kernel builders consume) and the explicit ring-transpose
path beside ``jax.lax.all_to_all`` (parallel/decomp.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import rustpde_mpi_tpu as rp
from rustpde_mpi_tpu.bases import (
    Space2,
    cheb_dirichlet,
    cheb_dirichlet_neumann,
    chebyshev,
    fourier_r2c,
    fourier_r2c_split,
)
from rustpde_mpi_tpu.ops.pallas_conv import FusedConv


def _data(sp, seed=0):
    rng = np.random.default_rng(seed)
    nx, ny = sp.shape_physical
    ux = jnp.asarray(rng.standard_normal((nx, ny)))
    uy = jnp.asarray(rng.standard_normal((nx, ny)))
    vhat = sp.forward(jnp.asarray(rng.standard_normal((nx, ny))))
    return ux, uy, vhat


def _check(fc, ux, uy, vhat, atol, with_bc=False, seed=5):
    if with_bc:
        rng = np.random.default_rng(seed)
        nx, ny = fc.space_in.shape_physical
        bcx = jnp.asarray(rng.standard_normal((nx, ny)))
        bcy = jnp.asarray(rng.standard_normal((nx, ny)))
        args = (ux, uy, vhat, bcx, bcy)
    else:
        args = (ux, uy, vhat)
    ref = np.asarray(fc.reference(*args))
    out = np.asarray(fc.apply(*args))
    np.testing.assert_allclose(out, ref, atol=atol * max(1.0, np.abs(ref).max()))
    return out, ref


def test_confined_sep_layout(monkeypatch):
    """The TPU layout: sep Chebyshev x sep Chebyshev, matmul transforms."""
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    sp = Space2(cheb_dirichlet(33), cheb_dirichlet(33), method="matmul", sep=True)
    fs = Space2(chebyshev(33), chebyshev(33), method="matmul", sep=True)
    assert all(sp.sep) and all(fs.sep)
    fc = FusedConv(sp, fs, (1.0, 1.0))
    ux, uy, vhat = _data(sp)
    _check(fc, ux, uy, vhat, 1e-12)
    _check(fc, ux, uy, vhat, 1e-12, with_bc=True)


def test_confined_natural_layout_fft_reference():
    """Non-sep CPU-default layout (fft method): same linear operator, so
    the kernel still matches — the cross-method parity case."""
    sp = Space2(cheb_dirichlet(17), cheb_dirichlet(17))
    fs = Space2(chebyshev(17), chebyshev(17))
    assert not any(sp.sep)
    fc = FusedConv(sp, fs, (1.0, 2.0))
    ux, uy, vhat = _data(sp)
    _check(fc, ux, uy, vhat, 1e-12)


def test_periodic_complex_layout():
    """Complex r2c Fourier x Chebyshev (the CPU periodic layout): the
    kernel converts to split Re/Im planes at the chain boundary."""
    sp = Space2(fourier_r2c(16), cheb_dirichlet(17))
    fs = Space2(fourier_r2c(16), chebyshev(17))
    assert sp.spectral_is_complex
    fc = FusedConv(sp, fs, (1.0, 1.0))
    ux, uy, vhat = _data(sp)
    out, _ = _check(fc, ux, uy, vhat, 1e-12, with_bc=True)
    assert np.iscomplexobj(out)


def test_split_sep_layout(monkeypatch):
    """Split Re/Im Fourier x sep Chebyshev — the real multichip periodic
    layout (and the hc mixed-BC temp space rides the same path)."""
    monkeypatch.setenv("RUSTPDE_SEP", "1")
    sp = Space2(fourier_r2c_split(16), cheb_dirichlet(17), method="matmul", sep=True)
    fs = Space2(fourier_r2c_split(16), chebyshev(17), method="matmul", sep=True)
    assert sp.sep == (False, True)
    fc = FusedConv(sp, fs, (1.0, 1.0))
    ux, uy, vhat = _data(sp)
    _check(fc, ux, uy, vhat, 1e-12, with_bc=True)
    # mixed-BC y base (no parity structure -> conjugated dense operators)
    sp2 = Space2(fourier_r2c_split(16), cheb_dirichlet_neumann(17), method="matmul", sep=True)
    fc2 = FusedConv(sp2, fs, (1.0, 1.0))
    ux, uy, vhat = _data(sp2, seed=3)
    _check(fc2, ux, uy, vhat, 1e-12)


def test_dealias_mask_equivalence(monkeypatch):
    """The kernel's row-drop epilogue reproduces the 2/3-rule mask exactly:
    dead rows are hard zeros, live rows match the dense masked forward."""
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    sp = Space2(cheb_dirichlet(33), cheb_dirichlet(33), method="matmul", sep=True)
    fs = Space2(chebyshev(33), chebyshev(33), method="matmul", sep=True)
    fc = FusedConv(sp, fs, (1.0, 1.0))
    ux, uy, vhat = _data(sp)
    out = np.asarray(fc.apply(ux, uy, vhat))
    mask = fs.dealias_mask()
    assert np.all(out[mask == 0.0] == 0.0)
    assert np.any(out[mask == 1.0] != 0.0)
    np.testing.assert_array_equal(out * mask, out)


def test_f32_dtype():
    sp = Space2(cheb_dirichlet(17), cheb_dirichlet(17))
    fs = Space2(chebyshev(17), chebyshev(17))
    fc32 = FusedConv(sp, fs, (1.0, 1.0), cast=np.float32)
    ux, uy, vhat = _data(sp)
    ref = np.asarray(fc32.reference(ux, uy, vhat))
    out = np.asarray(fc32.apply(ux.astype(np.float32), uy.astype(np.float32),
                                vhat.astype(np.float32)))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, ref, atol=2e-5 * max(1.0, np.abs(ref).max()))


def test_f64_hybrid_cast(monkeypatch):
    """RUSTPDE_F64_HYBRID=1 convention: f32-stored matrices, f64 state cast
    through the chain — f64 in/out dtype, f32-level agreement."""
    from rustpde_mpi_tpu.ops.pallas_conv import hybrid_cast

    monkeypatch.setenv("RUSTPDE_F64_HYBRID", "1")
    assert hybrid_cast() == np.float32
    sp = Space2(cheb_dirichlet(17), cheb_dirichlet(17))
    fs = Space2(chebyshev(17), chebyshev(17))
    fc = FusedConv(sp, fs, (1.0, 1.0), cast=hybrid_cast())
    ux, uy, vhat = _data(sp)
    ref = np.asarray(fc.reference(ux, uy, vhat, fast=False))
    out = np.asarray(fc.apply(ux, uy, vhat))
    assert out.dtype == np.float64
    np.testing.assert_allclose(out, ref, atol=2e-5 * max(1.0, np.abs(ref).max()))


def test_vmapped_ensemble_batching():
    """vmap over the kernel == per-member applies (the ensemble engine's
    batched dispatch re-vmaps the step jaxpr through the pallas_call)."""
    sp = Space2(cheb_dirichlet(17), cheb_dirichlet(17))
    fs = Space2(chebyshev(17), chebyshev(17))
    fc = FusedConv(sp, fs, (1.0, 1.0))
    rng = np.random.default_rng(0)
    K = 3
    ux = jnp.asarray(rng.standard_normal((K, 17, 17)))
    uy = jnp.asarray(rng.standard_normal((K, 17, 17)))
    vhat = jnp.stack(
        [sp.forward(jnp.asarray(rng.standard_normal((17, 17)))) for _ in range(K)]
    )
    batched = np.asarray(jax.vmap(fc.apply)(ux, uy, vhat))
    solo = np.stack(
        [np.asarray(fc.apply(ux[k], uy[k], vhat[k])) for k in range(K)]
    )
    np.testing.assert_array_equal(batched, solo)


# -- model integration (RUSTPDE_CONV_KERNEL knob) -----------------------------


def _build_navier(periodic, **kw):
    nx, ny = (16, 17) if periodic else (17, 17)
    m = rp.Navier2D(nx, ny, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=periodic, **kw)
    m.set_velocity(0.1, 1.0, 1.0)
    m.set_temperature(0.1, 1.0, 1.0)
    return m


@pytest.mark.parametrize("periodic", [False, True])
def test_navier_step_knob_parity(monkeypatch, periodic):
    """RUSTPDE_CONV_KERNEL=pallas: 5-step trajectories match the dense
    chain at fp-epsilon (documented tolerance 1e-13 absolute, f64)."""
    dense = _build_navier(periodic)
    dense.update_n(5)
    monkeypatch.setenv("RUSTPDE_CONV_KERNEL", "pallas")
    pal = _build_navier(periodic)
    assert pal._conv_impl is not None
    pal.update_n(5)
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_allclose(
            np.asarray(getattr(pal.state, attr)),
            np.asarray(getattr(dense.state, attr)),
            atol=1e-13,
            err_msg=attr,
        )
    assert pal.eval_nu() == pytest.approx(dense.eval_nu(), abs=1e-12)


def test_navier_ensemble_knob_parity(monkeypatch):
    """The vmapped ensemble dispatch rides the kernel path unchanged."""
    monkeypatch.setenv("RUSTPDE_CONV_KERNEL", "pallas")
    model = _build_navier(False)
    ens = rp.NavierEnsemble.from_seeds(model, seeds=range(2))
    ens.update_n(3)
    assert ens.alive().all()
    solo = _build_navier(False)
    solo.init_random(0.1, seed=0)
    solo.update_n(3)
    np.testing.assert_allclose(
        np.asarray(ens.state.temp[0]), np.asarray(solo.state.temp), atol=1e-13
    )


def test_step_flops_counts_pallas(monkeypatch):
    """profiling.step_flops prices the opaque pallas_call (registry +
    kernel-jaxpr fallback) — the MFU gauges stay honest on the kernel
    path instead of silently under-reporting."""
    from rustpde_mpi_tpu.utils import profiling

    dense = _build_navier(False)
    f_dense = profiling.step_flops(dense, method="jaxpr")
    monkeypatch.setenv("RUSTPDE_CONV_KERNEL", "pallas")
    pal = _build_navier(False)
    f_pal = profiling.step_flops(pal, method="jaxpr")
    # the conv family is ~half the step's dots: pricing it at the unfused
    # dense chain's useful flops keeps the two counts within ~2x
    assert f_pal > 0.5 * f_dense
    assert f_pal < 4.0 * f_dense
    # registry override is live (shape-keyed name: distinct chain shapes
    # must not collide on one entry)
    assert any(k.startswith("fused_conv_") for k in profiling.PALLAS_FLOPS)


def test_axis_operator_accessor():
    """The stable (matrix, parity, dealias_rows) accessor reproduces the
    private folded device applies exactly — one source of truth for the
    fold structure."""
    rng = np.random.default_rng(0)
    b = cheb_dirichlet(17)
    for sep in (False, True):
        for key in ("fwd", "bwd", "synthesis", ("bwd_grad", 1)):
            op = b.axis_operator(key, sep=sep)
            assert op.parity in ((False, False), (False, True), (True, False))
            x = rng.standard_normal((op.matrix.shape[1], 3))
            if sep:
                fm = b._sep_dev(key)
                ref = np.asarray(fm.apply(jnp.asarray(x), 0))
            else:
                if key == "fwd":
                    ref = np.asarray(b.forward(jnp.asarray(x), 0, "matmul"))
                elif key == "bwd":
                    ref = np.asarray(b.backward(jnp.asarray(x), 0, "matmul"))
                elif key == "synthesis":
                    ref = np.asarray(b.backward_ortho(jnp.asarray(x), 0, "matmul"))
                else:
                    ref = np.asarray(
                        b.backward_ortho(b.gradient(jnp.asarray(x), 1, 0), 0, "matmul")
                    )
            np.testing.assert_allclose(op.matrix @ x, ref, atol=1e-11)
    # dealias cut bookkeeping
    op = b.axis_operator("fwd_cut", sep=True)
    assert op.dealias_rows == b.m * 2 // 3
    kept = op.kept_rows
    from rustpde_mpi_tpu.ops.folded import parity_perm

    assert np.array_equal(np.sort(parity_perm(b.m)[kept]), np.arange(op.dealias_rows))


# -- explicit ring transpose (parallel/decomp.py) -----------------------------


def test_ring_transpose_matches_all_to_all():
    """The shift-permute ring body is value-identical to the tiled
    all_to_all on the virtual mesh, both directions, odd extents included."""
    from rustpde_mpi_tpu.parallel import make_mesh
    from rustpde_mpi_tpu.parallel.decomp import Decomp2d

    mesh = make_mesh()
    for shape in [(16, 16), (33, 17)]:
        d = Decomp2d(shape, mesh)
        a = jnp.asarray(np.random.default_rng(1).standard_normal(shape))
        for x2y in (True, False):
            go = d.transpose_x_to_y if x2y else d.transpose_y_to_x
            ref = np.asarray(go(a, method="alltoall"))
            ring = np.asarray(go(a, method="ring"))
            np.testing.assert_array_equal(ref, np.asarray(a))
            np.testing.assert_array_equal(ring, ref)


def test_ring_transpose_knob_roundtrip(monkeypatch):
    """RUSTPDE_TRANSPOSE=ring routes the default path; x2y∘y2x == id."""
    from rustpde_mpi_tpu.parallel import make_mesh
    from rustpde_mpi_tpu.parallel.decomp import Decomp2d, transpose_method

    monkeypatch.setenv("RUSTPDE_TRANSPOSE", "ring")
    assert transpose_method() == "ring"
    d = Decomp2d((24, 16), make_mesh())
    a = jnp.asarray(np.random.default_rng(2).standard_normal((24, 16)))
    np.testing.assert_array_equal(
        np.asarray(d.transpose_y_to_x(d.transpose_x_to_y(a))), np.asarray(a)
    )


def test_manual_conv_region_matches_dense(monkeypatch):
    """parallel/decomp.ShardedConv (the manual split-sep region) == the
    serial dense chain, under both transpose methods."""
    from rustpde_mpi_tpu.parallel import make_mesh, use_mesh
    from rustpde_mpi_tpu.parallel.decomp import ShardedConv

    monkeypatch.setenv("RUSTPDE_SEP", "1")
    sp = Space2(fourier_r2c_split(16), cheb_dirichlet(17), method="matmul")
    fs = Space2(fourier_r2c_split(16), chebyshev(17), method="matmul")
    fc = FusedConv(sp, fs, (1.0, 1.0))  # serial reference chain
    ux, uy, vhat = _data(sp)
    ref = np.asarray(fc.reference(ux, uy, vhat))
    mesh = make_mesh()
    for method in ("alltoall", "ring"):
        monkeypatch.setenv("RUSTPDE_TRANSPOSE", method)
        sc = ShardedConv(sp, fs, (1.0, 1.0), mesh)
        with use_mesh(mesh):
            out = np.asarray(jax.jit(sc.apply)(ux, uy, vhat))
        np.testing.assert_allclose(out, ref, atol=1e-13, err_msg=method)
