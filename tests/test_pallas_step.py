"""Pallas fused step (Helmholtz/Poisson megakernel): interpreter-mode parity.

Mirrors tests/test_pallas_conv.py for the implicit half of the step
(ops/pallas_step.py, RUSTPDE_STEP_KERNEL knob): the fused solve/projection
kernels run in Pallas interpreter mode on CPU so tier-1 exercises the real
kernel path on every layout without a chip.  Documented tolerances: the
fused chain computes the same linear solves with one reassociation (tiled
GEMM accumulation vs the dense solver chain), so 5-step trajectory parity
is fp-epsilon in f64 — the acceptance floor is 1e-12 on the physical-field
scale, observed ~1e-15.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import rustpde_mpi_tpu as rp
from rustpde_mpi_tpu.ops.pallas_step import (
    FusedStage,
    StageTerm,
    build_model_step,
    step_kernel_choice,
    step_traffic_estimate,
)

_LAYOUTS = {
    # CPU-default confined: non-sep Chebyshev x Chebyshev, fft transforms
    "confined": (False, {}),
    # CPU-default periodic: complex r2c Fourier x Chebyshev
    "periodic": (True, {}),
    # TPU confined layout: sep Chebyshev x sep Chebyshev, matmul transforms
    "confined_sep": (False, {"RUSTPDE_FORCE_TPU_PATH": "1"}),
    # TPU periodic layout: split Re/Im Fourier x sep Chebyshev
    "split_sep": (True, {"RUSTPDE_FORCE_TPU_PATH": "1", "RUSTPDE_SEP": "1"}),
}


def _build_navier(periodic, nx=None, ny=None, **kw):
    if nx is None:
        nx, ny = (16, 17) if periodic else (17, 17)
    m = rp.Navier2D(nx, ny, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=periodic, **kw)
    m.set_velocity(0.1, 1.0, 1.0)
    m.set_temperature(0.1, 1.0, 1.0)
    return m


def _assert_trajectory_parity(dense, pal, steps=3, atol=1e-13):
    dense.update_n(steps)
    pal.update_n(steps)
    attrs = ["temp", "velx", "vely", "pres", "pseu"]
    if hasattr(dense.state, "scal"):
        attrs.append("scal")
    for attr in attrs:
        np.testing.assert_allclose(
            np.asarray(getattr(pal.state, attr)),
            np.asarray(getattr(dense.state, attr)),
            atol=atol,
            err_msg=attr,
        )
    assert pal.eval_nu() == pytest.approx(dense.eval_nu(), abs=1e-12)


# -- model-level dense-vs-pallas parity, all four layouts ---------------------


@pytest.mark.parametrize("layout", list(_LAYOUTS))
def test_navier_step_knob_parity(monkeypatch, layout):
    """RUSTPDE_STEP_KERNEL=pallas: 3-step trajectories match the dense
    solver chain at fp-epsilon per layout (acceptance floor 1e-12 on the
    physical-field scale; observed ~1e-15)."""
    periodic, env = _LAYOUTS[layout]
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    # the sep layouts are exercised at 33^2 like the conv suite (17 is
    # below the auto-sep threshold; FORCE_TPU_PATH pins the layout anyway)
    nx, ny = ((16, 17) if periodic else (33, 33)) if env else (None, None)
    dense = _build_navier(periodic, nx, ny)
    assert dense._step_impl is None  # default knob: byte-identical dense path
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    assert step_kernel_choice() == "pallas"
    pal = _build_navier(periodic, nx, ny)
    assert pal._step_impl is not None
    _assert_trajectory_parity(dense, pal)


@pytest.mark.slow
def test_navier_step_knob_parity_scenario(monkeypatch):
    """Coriolis + passive-scalar scenario: the extra stage terms (rotation
    coupling) and the scal stage ride the fused path."""
    scn = {"coriolis": 2.0, "passive_scalar": True, "scalar_kappa": None}
    dense = _build_navier(False, scenario=scn)
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    pal = _build_navier(False, scenario=scn)
    ic = np.random.default_rng(0).standard_normal((17, 17)) * 0.1
    dense.set_field("scal", ic)
    pal.set_field("scal", ic)
    assert pal._step_impl is not None and "scal" in pal._step_impl
    _assert_trajectory_parity(dense, pal)


@pytest.mark.slow
def test_navier_step_knob_parity_solid(monkeypatch):
    """The solid-mask penalization epilogue is shared by both branches of
    _make_step; the fused solves must compose with it unchanged."""
    dense = _build_navier(False)
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    pal = _build_navier(False)
    mask = np.zeros((17, 17))
    mask[6:10, 6:10] = 1.0
    dense.set_solid(mask, 0.3, 1e-2)
    pal.set_solid(mask, 0.3, 1e-2)
    _assert_trajectory_parity(dense, pal)


@pytest.mark.slow
def test_set_dt_rebuilds_step_kernels(monkeypatch):
    """dt appears in the Helmholtz factors and lift constants: a dt rung
    change must rebuild the fused stages (the _DT_ARTIFACTS contract)."""
    dense = _build_navier(False)
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    pal = _build_navier(False)
    old = pal._step_impl
    dense.set_dt(2.5e-3)
    pal.set_dt(2.5e-3)
    assert pal._step_impl is not None and pal._step_impl is not old
    _assert_trajectory_parity(dense, pal)


# -- stage-level kernel-vs-reference parity -----------------------------------


def _stage_inputs(m, rng):
    def rnd(sp):
        return sp.forward(jnp.asarray(rng.standard_normal(sp.shape_physical)))

    sp_u, sp_p, sp_t = m.velx_space, m.pres_space, m.temp_space
    sp_f, sp_q = m.field_space, m.pseu_space
    ins = {
        "velx": [rnd(sp_u), rnd(sp_p), rnd(sp_f)],
        "vely": [rnd(sp_u), rnd(sp_p), rnd(sp_t), rnd(sp_f)],
        "temp": [rnd(sp_t), rnd(sp_f)],
        "scal": [rnd(sp_t), rnd(sp_f)],
        "div": [rnd(sp_u), rnd(sp_u)],
        "poisson": [rnd(sp_q)],
        "projx": [rnd(sp_q)],
        "projy": [rnd(sp_q)],
    }
    if m._coriolis():
        ins["velx"].append(rnd(sp_u))
        ins["vely"].append(rnd(sp_u))
    return ins


@pytest.mark.parametrize("periodic", [False, True])
def test_stage_apply_matches_reference(monkeypatch, periodic):
    """Every fused stage: pallas_call == the same padded chain as plain XLA
    dots (kernel-plumbing parity, isolated from the model surroundings)."""
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    m = _build_navier(periodic)
    rng = np.random.default_rng(7)
    ins = _stage_inputs(m, rng)
    for name, stage in m._step_impl.items():
        xs = ins[name]
        ref = np.asarray(stage.reference(*xs))
        out = np.asarray(stage.apply(*xs))
        assert out.dtype == ref.dtype
        np.testing.assert_allclose(
            out, ref, atol=1e-12 * max(1.0, np.abs(ref).max()), err_msg=name
        )


def test_poisson_stage_pins_singular_mode(monkeypatch):
    """The pressure Poisson kernel's output mask hard-zeros the singular
    mean mode — the downstream pin_zero_mode is then the identity."""
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    m = _build_navier(False)
    rng = np.random.default_rng(3)
    div = m.pseu_space.forward(
        jnp.asarray(rng.standard_normal(m.pseu_space.shape_physical))
    )
    out = m._step_impl["poisson"].apply(div)
    assert np.asarray(out)[0, 0] == 0.0
    np.testing.assert_array_equal(
        np.asarray(m.pseu_space.pin_zero_mode(out)), np.asarray(out)
    )


# -- dtype / cast contracts ---------------------------------------------------


def _toy_modal_stage(cast=None):
    rng = np.random.default_rng(0)
    r0, k0, k1, q1 = 9, 11, 13, 10
    terms = [
        StageTerm(rng.standard_normal((r0, k0)), rng.standard_normal((q1, k1)), False),
        StageTerm(rng.standard_normal((r0, k0)), rng.standard_normal((q1, k1)), False),
    ]
    dinv = 1.0 / (1.0 + np.arange(r0)[:, None] + np.arange(q1)[None, :])
    b0 = rng.standard_normal((r0, r0))
    b1 = rng.standard_normal((q1, q1))
    xs = [rng.standard_normal((k0, k1)) for _ in terms]
    return FusedStage("toy", terms, False, modal=(dinv, b0, b1), cast=cast), xs


def test_f32_cast_stage():
    stage, xs = _toy_modal_stage(cast=np.float32)
    xs = [jnp.asarray(x, dtype=jnp.float32) for x in xs]
    out = np.asarray(stage.apply(*xs))
    ref = np.asarray(stage.reference(*xs))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, ref, atol=2e-5 * max(1.0, np.abs(ref).max()))


def test_f64_hybrid_keeps_solves_in_f64(monkeypatch):
    """RUSTPDE_F64_HYBRID casts only the convection transforms to f32; the
    implicit solves stay f64 on BOTH paths (build_model_step passes
    cast=None), so knob parity holds at fp-epsilon even under hybrid."""
    monkeypatch.setenv("RUSTPDE_F64_HYBRID", "1")
    dense = _build_navier(False)
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    pal = _build_navier(False)
    for stage in pal._step_impl.values():
        assert stage._cast is None
    _assert_trajectory_parity(dense, pal)


# -- batching -----------------------------------------------------------------


def test_vmapped_stage_bit_equality(monkeypatch):
    """vmap over a fused stage == per-member applies, bit-identical (the
    ensemble engine re-vmaps the step jaxpr through the pallas_call)."""
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    m = _build_navier(False)
    stage = m._step_impl["velx"]
    rng = np.random.default_rng(1)
    K = 3
    xs = [
        jnp.stack([sp.forward(jnp.asarray(rng.standard_normal(sp.shape_physical)))
                   for _ in range(K)])
        for sp in (m.velx_space, m.pres_space, m.field_space)
    ]
    batched = np.asarray(jax.vmap(stage.apply)(*xs))
    solo = np.stack(
        [np.asarray(stage.apply(*(x[k] for x in xs))) for k in range(K)]
    )
    np.testing.assert_array_equal(batched, solo)


def test_navier_ensemble_knob_parity(monkeypatch):
    """The vmapped ensemble dispatch rides the fused solve path unchanged."""
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    model = _build_navier(False)
    ens = rp.NavierEnsemble.from_seeds(model, seeds=range(2))
    ens.update_n(3)
    assert ens.alive().all()
    solo = _build_navier(False)
    solo.init_random(0.1, seed=0)
    solo.update_n(3)
    np.testing.assert_allclose(
        np.asarray(ens.state.temp[0]), np.asarray(solo.state.temp), atol=1e-13
    )


# -- governed bit-path contracts ----------------------------------------------


def test_recompile_flat_across_knob_flips(monkeypatch):
    """The knob binds at model build: flipping RUSTPDE_STEP_KERNEL under a
    LIVE model must not leak rebuilds (recompile_count stays flat) and must
    not change which path the live model runs."""
    dense = _build_navier(False)
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    pal = _build_navier(False)
    before = (dense.recompile_count, pal.recompile_count)
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "dense")
    pal.update_n(4)
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    dense.update_n(4)
    assert (dense.recompile_count, pal.recompile_count) == before
    assert dense._step_impl is None and pal._step_impl is not None


def test_default_dense_builds_no_kernels(monkeypatch):
    """Knob default `dense`: no fused stages are built, the step closure
    takes the existing dense branch — byte-identical prior behavior."""
    monkeypatch.delenv("RUSTPDE_STEP_KERNEL", raising=False)
    assert step_kernel_choice() == "dense"
    m = _build_navier(False)
    assert m._step_impl is None


# -- profiling / traffic accounting -------------------------------------------


def test_step_flops_registered(monkeypatch):
    """Every fused stage registers analytic unpadded flops under its
    shape-keyed kernel name, and the jaxpr pricing of the fused step stays
    comparable to the dense chain (MFU gauges survive the knob flip)."""
    from rustpde_mpi_tpu.utils import profiling

    dense = _build_navier(False)
    f_dense = profiling.step_flops(dense, method="jaxpr")
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    pal = _build_navier(False)
    f_pal = profiling.step_flops(pal, method="jaxpr")
    for stage in pal._step_impl.values():
        assert profiling.PALLAS_FLOPS[stage.kernel_name] == stage.flops
        assert stage.flops > 0
    assert f_pal > 0.5 * f_dense
    assert f_pal < 4.0 * f_dense


def test_step_traffic_estimate(monkeypatch):
    """The HBM-bytes-per-step model: at toy grids the LANE-quantized
    operator padding dominates (ratio < 1 — honest, not hidden); at
    production grids the fused path moves strictly less than the dense
    dispatch chain.  The crossover sits between 129^2 and 257^2."""
    monkeypatch.setenv("RUSTPDE_STEP_KERNEL", "pallas")
    toy = step_traffic_estimate(_build_navier(False))
    assert toy["pallas_bytes_per_step"] > 0
    assert toy["dense_bytes_per_step"] > 0
    big = step_traffic_estimate(
        rp.Navier2D(257, 257, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=False)
    )
    assert big["traffic_ratio"] > 1.0 > toy["traffic_ratio"]


def test_build_model_step_standalone():
    """build_model_step works on a dense-knob model too (bench/traffic
    probes build throwaway kernel sets without flipping the model)."""
    m = _build_navier(False)
    assert m._step_impl is None
    impl = build_model_step(m, interpret=True)
    assert set(impl) >= {"velx", "vely", "temp", "div", "poisson", "projx", "projy"}
