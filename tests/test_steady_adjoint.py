"""Navier2DAdjoint steady-state finder tests (SURVEY.md S2 row
`Navier2DAdjoint`; /root/reference/src/navier_stokes/steady_adjoint.rs)."""

import numpy as np
import pytest

from rustpde_mpi_tpu import Navier2D, Navier2DAdjoint
from rustpde_mpi_tpu.models.steady_adjoint import DT_NAVIER


def _adjoint(nx=33, ra=1e4, dt=5e-3, bc="rbc"):
    model = Navier2DAdjoint.new_confined(nx, nx, ra, 1.0, dt, 1.0, bc)
    model.set_temperature(0.5, 1.0, 1.0)
    model.set_velocity(0.5, 1.0, 1.0)
    return model


@pytest.mark.slow
def test_residual_decreases():
    model = _adjoint()
    model.update_n(50)
    res_early = model.residual()
    model.update_n(450)
    assert model.residual() < res_early
    assert np.isfinite(model.div_norm())


@pytest.mark.slow
def test_subcritical_converges_to_conduction():
    """Ra=100 << Ra_c from zero fields: the descent settles into the
    conduction state (hydrostatic pressure builds over the first iterations),
    the residual drops below RES_TOL, exit() fires, and Nu -> 1."""
    model = Navier2DAdjoint.new_confined(17, 17, 100.0, 1.0, 1e-3, 1.0, "rbc")
    converged = False
    for _ in range(5):
        model.update_n(200)
        if model.exit():
            converged = True
            break
    assert converged, f"residual {model.residual()} after 1000 iterations"
    assert model.residual() < 1e-7
    assert model.eval_nu() == pytest.approx(1.0, abs=1e-4)


@pytest.mark.slow
def test_supercritical_descends_toward_steady_state():
    """Ra=5e3 > Ra_c: the residual decreases monotonically-ish and the state
    approaches a convective steady state whose forward-DNS Nu drift is small.
    (Full convergence to RES_TOL is exercised by examples/navier_rbc_steady.py
    — it takes tens of thousands of iterations.)"""
    model = _adjoint(nx=17, ra=5e3, dt=1e-2)
    model.update_n(300)
    res_early = model.residual()
    model.update_n(1200)
    res = model.residual()
    assert res < res_early
    assert res < 1e-2
    nu_adj = model.eval_nu()
    assert 1.0 < nu_adj < 3.0

    # forward DNS check: the near-steady state should evolve only slowly
    dns = Navier2D(17, 17, 5e3, 1.0, DT_NAVIER, 1.0, "rbc", periodic=False)
    dns.state = dns.state._replace(
        temp=model.state.temp,
        velx=model.state.velx,
        vely=model.state.vely,
        pres=model.state.pres,
        pseu=model.state.pseu,
    )
    nu0 = dns.eval_nu()
    dns.update_n(500)
    assert dns.eval_nu() == pytest.approx(nu0, rel=5e-2)


def test_write_read_roundtrip(tmp_path):
    model = _adjoint(nx=17)
    model.update_n(10)
    fname = str(tmp_path / "adjoint.h5")
    model.write(fname)
    other = _adjoint(nx=17)
    other.read(fname)
    np.testing.assert_allclose(
        np.asarray(other.state.temp), np.asarray(model.state.temp), atol=1e-14
    )
