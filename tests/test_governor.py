"""Stability-governor tests: on-device CFL/energy sentinels, pre-divergence
early-exit with in-memory rollback, the rung-cached dt ladder, regrowth,
ensemble batch-max CFL reduction, and the governed ResilientRunner paths
(utils/governor.py + the sentinel chunks in models/navier.py,
models/ensemble.py)."""

import json
import os

import numpy as np
import pytest

from rustpde_mpi_tpu import (
    DivergenceError,
    Navier2D,
    NavierEnsemble,
    ResilientRunner,
    integrate,
)
from rustpde_mpi_tpu.config import NavierConfig, ResilienceConfig, StabilityConfig
from rustpde_mpi_tpu.utils.governor import (
    ChunkStatus,
    DtLadder,
    StabilityGovernor,
)
from rustpde_mpi_tpu.utils.resilience import FaultPlan


def _build(dt=0.01, stability=None):
    model = Navier2D(17, 17, 1e4, 1.0, dt, 1.0, "rbc", periodic=False)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.write_intervall = 1e9
    if stability is not None:
        model.set_stability(stability)
    return model


def _events(run_dir):
    with open(os.path.join(run_dir, "journal.jsonl"), encoding="utf-8") as fh:
        return [json.loads(line) for line in fh]


def _status(**kw):
    base = dict(
        requested=50,
        steps_done=50,
        finite=True,
        cfl_ok=True,
        pre_divergence=False,
        cfl_max=0.1,
        ke=1.0,
        ke_growth_max=1.0,
        div_max=0.01,
        dt=0.01,
    )
    base.update(kw)
    return ChunkStatus(**base)


# -- ladder + control law (host-side units) -----------------------------------


def test_dt_ladder_quantization():
    lad = DtLadder(1e-2, ratio=2.0, dt_min=1e-3, dt_max=4e-2)
    assert lad.dt(0) == 1e-2  # the anchor is always rung 0 exactly
    assert lad.top == 2 and lad.bottom == -3
    assert lad.dt(lad.top) == pytest.approx(4e-2)
    assert lad.dt(lad.bottom) == pytest.approx(1.25e-3)
    assert lad.dt(-99) == lad.dt(lad.bottom)  # clamped
    # every visit to a rung yields the identical float (the cache contract)
    assert lad.dt(-1) is lad.dt(-1) or lad.dt(-1) == lad.dt(-1)
    assert lad.rung_for(1e-2) == 0
    assert lad.rung_for(5.1e-3) == -1  # nearest in log space
    assert lad.rung_for(1e-9) == lad.bottom
    # rungs needed to bring an observed CFL back to target
    assert lad.rungs_to_target(2.0, 0.5) == 2
    assert lad.rungs_to_target(0.9, 0.5) == 1
    assert lad.rungs_to_target(0.3, 0.5) == 1  # always at least one
    assert lad.rungs_to_target(float("inf"), 0.5) == len(lad)
    with pytest.raises(ValueError):
        DtLadder(1e-2, ratio=0.9)
    with pytest.raises(ValueError):
        DtLadder(1e-2, dt_min=2e-2)  # dt_min above the anchor


def test_governor_control_law():
    cfg = StabilityConfig(
        target_cfl=0.5, max_cfl=1.0, ladder_ratio=2.0, dt_min=1e-3, grow_after=2
    )
    gov = StabilityGovernor(cfg, 1e-2)
    # healthy chunk in the dead band: no action
    assert gov.on_chunk(_status(cfl_max=0.4)).action == "ok"
    # pre-divergence: retry at a rung that predicts cfl <= target
    d = gov.on_chunk(
        _status(pre_divergence=True, cfl_ok=False, cfl_max=1.6, steps_done=3)
    )
    assert d.action == "retry"
    assert d.dt == pytest.approx(2.5e-3)  # 1.6 -> 0.4 needs 2 rungs
    assert gov.health.pre_divergence_catches == 1
    assert gov.health.rollbacks_avoided == 1
    # proactive shrink above shrink_cfl (default 0.85*max_cfl), no rollback
    d = gov.on_chunk(_status(cfl_max=0.9, dt=2.5e-3))
    assert d.action == "adjust" and d.dt < 2.5e-3
    # regrowth: grow_after healthy chunks with predicted cfl under target
    assert gov.on_chunk(_status(cfl_max=0.2, dt=d.dt)).action == "ok"
    d2 = gov.on_chunk(_status(cfl_max=0.2, dt=d.dt))
    assert d2.action == "adjust" and d2.dt == pytest.approx(2.0 * d.dt)
    # NaN chunks belong to the reactive machinery
    assert gov.on_chunk(_status(finite=False, cfl_max=float("nan"))).action == "ok"
    # bottom rung still tripping: give up (reactive path takes over)
    gov.rung = gov.ladder.bottom
    d = gov.on_chunk(_status(pre_divergence=True, cfl_ok=False, cfl_max=2.0))
    assert d.action == "give_up"


def test_align_floors_and_keeps_trajectory_honest():
    """align() (reactive rollback / resume re-anchoring) must round DOWN —
    nearest-rung rounding would restore the very dt that just diverged for
    any backoff milder than sqrt(ratio) — and must record on-ladder external
    changes in the health trajectory instead of overwriting history."""
    cfg = StabilityConfig(dt_min=1e-4)
    gov = StabilityGovernor(cfg, 2e-3)
    # a 0.8x reactive backoff: nearest rung would be 0 (the diverged dt!)
    assert gov.align(1.6e-3, step=5) == pytest.approx(1e-3)
    assert gov.rung == -1
    assert gov.health.dt_trajectory[-1] == (5, pytest.approx(1e-3))
    # an exactly-on-ladder backoff (the 0.5 x ratio-2 default) needs no
    # set_dt but still lands in the trajectory/extrema bookkeeping
    gov2 = StabilityGovernor(cfg, 2e-3)
    d = gov2.on_chunk(_status(cfl_max=0.9, dt=2e-3), step=10)
    assert d.action == "adjust"
    n_before = len(gov2.health.dt_trajectory)
    assert gov2.align(2.5e-4, step=30) is None
    assert len(gov2.health.dt_trajectory) == n_before + 1
    assert gov2.health.dt_trajectory[-1] == (30, pytest.approx(2.5e-4))
    assert gov2.health.dt_trajectory[-2][0] == 10  # history preserved
    assert gov2.health.dt_min_seen == pytest.approx(2.5e-4)


def test_governor_kills_persistently_pinned_members():
    cfg = StabilityConfig(member_pin_patience=2, dt_min=1e-3)
    gov = StabilityGovernor(cfg, 1e-2)
    pinned = _status(
        pre_divergence=True,
        cfl_ok=False,
        cfl_max=1.5,
        cfl_members=(0.1, 1.5, 0.2),
        pinned=(False, True, False),
    )
    # first pin: a dt drop is tried
    assert gov.on_chunk(pinned).action == "retry"
    # second consecutive pin of the SAME member: feed it to respawn_dead
    d = gov.on_chunk(pinned)
    assert d.action == "kill_members" and d.members == (1,)
    assert gov.health.members_killed == 1
    # a healthy chunk resets the pin counters
    gov2 = StabilityGovernor(cfg, 1e-2)
    assert gov2.on_chunk(pinned).action == "retry"
    assert gov2.on_chunk(_status()).action == "ok"
    assert gov2.on_chunk(pinned).action == "retry"  # count restarted


# -- sentinel chunks on the model ---------------------------------------------


def test_governed_stable_run_bit_identical(tmp_path):
    """A governed run at an already-stable dt must be BIT-identical to the
    ungoverned run: the sentinel step variant adds reductions over arrays
    the step already materializes, never touching the state math, and the
    governor in the dead band issues no dt change."""
    r1 = ResilientRunner(
        _build(),
        max_time=0.2,
        save_intervall=0.05,
        run_dir=str(tmp_path / "plain"),
        checkpoint_every_s=None,
    )
    s1 = r1.run()
    r2 = ResilientRunner(
        _build(),
        max_time=0.2,
        save_intervall=0.05,
        run_dir=str(tmp_path / "governed"),
        checkpoint_every_s=None,
        stability=StabilityConfig(),
    )
    s2 = r2.run()
    assert s2["outcome"] == "done" and s1["outcome"] == "done"
    for attr in ("temp", "velx", "vely", "pres", "pseu"):
        np.testing.assert_array_equal(
            np.asarray(getattr(r1.pde.state, attr)),
            np.asarray(getattr(r2.pde.state, attr)),
            err_msg=attr,
        )
    health = s2["health"]
    assert health["pre_divergence_catches"] == 0
    assert health["dt_adjusts"] == 0
    assert health["cfl_max"] < 1.0
    assert s1["health"] is None  # ungoverned runs carry no telemetry


@pytest.mark.slow
def test_spike_caught_pre_divergence_in_memory(tmp_path):
    """The acceptance demo: a deterministic velocity spike.  Governed, the
    CFL sentinel early-exits the chunk BEFORE NaNs, the rollback happens in
    memory and dt descends the ladder — zero reactive checkpoint restores.
    Ungoverned, the same spike grows into NaN divergence and needs the
    checkpoint-rollback path (>= 1 retry)."""
    gov_dir = str(tmp_path / "gov")
    r1 = ResilientRunner(
        _build(),
        max_time=0.5,
        save_intervall=0.05,
        run_dir=gov_dir,
        checkpoint_every_s=None,
        max_retries=2,
        fault="spike@10",
        spike_factor=200.0,
        stability=StabilityConfig(),
    )
    s1 = r1.run()
    assert s1["outcome"] == "done"
    assert s1["retries"] == 0  # NO reactive rollback
    assert s1["time"] == pytest.approx(0.5)
    assert np.isfinite(s1["nu"])
    assert s1["dt"] < 0.01  # descended the ladder
    events = [e["event"] for e in _events(gov_dir)]
    assert "pre_divergence" in events and "dt_adjust" in events
    assert "retry" not in events and "divergence" not in events
    # exactly the anchor + final checkpoints — recovery wrote none
    assert events.count("checkpoint") == 2
    health = s1["health"]
    assert health["pre_divergence_catches"] >= 1
    assert health["rollbacks_avoided"] >= 1
    assert health["cfl_max"] > 1.0  # the spike was seen...
    assert health["dt_trajectory"][0][1] == 0.01  # ...and the dt ladder walked

    ungov_dir = str(tmp_path / "ungov")
    r2 = ResilientRunner(
        _build(),
        max_time=0.5,  # the spike needs ~0.4 time units to grow into NaN

        save_intervall=0.05,
        run_dir=ungov_dir,
        checkpoint_every_s=None,
        max_retries=3,
        fault="spike@10",
        spike_factor=200.0,
    )
    try:
        s2 = r2.run()
        assert s2["retries"] >= 1  # survived, but only via checkpoint rollback
    except DivergenceError:
        pass  # or it never recovered — either way the governed run wins
    assert "divergence" in [e["event"] for e in _events(ungov_dir)]


def test_ungoverned_sentinels_break_cleanly():
    """Sentinels armed but no governor: a CFL trip rolls the chunk back,
    latches exit(), and plain integrate() stops at the finite rolled-back
    state instead of stepping into NaNs or looping forever."""
    model = _build(stability=StabilityConfig())
    model.update_n(4)
    model.state = model.state._replace(
        velx=model.state.velx * 200.0, vely=model.state.vely * 200.0
    )
    model._obs_cache = None
    t_spike = model.time
    assert integrate(model, 0.3, None) == "break"
    assert model.time == t_spike  # rolled back, not advanced
    assert bool(np.isfinite(np.asarray(model.state.temp)).all())
    model.clear_pre_divergence()
    assert not model.exit()


@pytest.mark.slow
def test_dt_ladder_cache_bounds_rejits():
    """Cycling the governor's dt ladder re-traces/refactorizes each rung at
    most once: revisits swap the cached artifacts back in (and the restored
    jit closures keep their identity, so XLA's executable cache hits)."""
    model = _build(stability=StabilityConfig())
    model.update_n(2)
    rungs = [0.01, 0.005, 0.0025, 0.00125]
    base = model.recompile_count
    for _ in range(3):  # three full down-up sweeps
        for dt in rungs + rungs[::-1]:
            model.set_dt(dt)
    assert model.recompile_count - base == len(rungs) - 1  # only first visits
    # cached rungs step correctly after a revisit
    model.set_dt(0.005)
    status = model.update_n(3)
    assert not status.pre_divergence and status.dt == 0.005
    fresh = _build(dt=0.005)
    fresh.state = model.state
    model.update_n(4)
    fresh.update_n(4)
    np.testing.assert_allclose(
        np.asarray(model.state.temp), np.asarray(fresh.state.temp), atol=1e-13
    )


@pytest.mark.slow
def test_ensemble_batch_max_cfl_matches_serial():
    """The ensemble's per-member CFL sentinel must equal stepping each
    member through the single-run sentinel path, and the batch reduction is
    exactly the max over members (members share the baked dt)."""
    model = _build(stability=StabilityConfig())
    ens = NavierEnsemble.from_seeds(model, seeds=range(3))
    members0 = [ens.member_state(i) for i in range(3)]
    status = ens.update_n(6)
    assert status.cfl_members is not None and len(status.cfl_members) == 3
    assert status.cfl_max == max(status.cfl_members)
    for i, m0 in enumerate(members0):
        solo = _build(stability=StabilityConfig())
        solo.state = m0
        r = solo.update_n(6)
        np.testing.assert_allclose(
            status.cfl_members[i], r.cfl_max, rtol=1e-12, err_msg=f"member {i}"
        )


def test_ensemble_spike_rolls_back_and_respawn_reproducible(tmp_path):
    """One spiked member pins the batch CFL ceiling: the whole chunk rolls
    back in memory (shared dt), mark_dead + respawn_dead revive it, and the
    config-carried respawn seed makes the revived state reproducible."""
    import jax

    def spiked_ensemble():
        model = _build(stability=StabilityConfig())
        ens = NavierEnsemble.from_seeds(model, seeds=range(3))
        ens.update_n(4)
        bad = jax.tree.map(lambda x: x * 300.0, ens.member_state(1))
        ens.set_member(1, bad._replace(temp=ens.member_state(1).temp))
        return ens

    ens = spiked_ensemble()
    snap = np.asarray(ens.state.velx).copy()
    status = ens.update_n(5)
    assert status.pre_divergence and status.pinned == (False, True, False)
    np.testing.assert_array_equal(np.asarray(ens.state.velx), snap)
    assert ens.exit()  # latched until a governor acts
    ens.clear_pre_divergence()
    ens.mark_dead([1])
    assert list(ens.alive()) == [True, False, True]
    ens.respawn_seed = 1234  # the config-carried stream
    assert ens.respawn_dead(amp=1e-3) == 1
    ens2 = spiked_ensemble()
    ens2.update_n(5)
    ens2.clear_pre_divergence()
    ens2.mark_dead([1])
    ens2.respawn_seed = 1234
    assert ens2.respawn_dead(amp=1e-3) == 1
    np.testing.assert_array_equal(
        np.asarray(ens.state.velx), np.asarray(ens2.state.velx)
    )


@pytest.mark.slow
def test_governor_climbs_back_up(tmp_path):
    """Regrowth: with headroom above the anchor (dt_max > dt0) and a calm
    flow, the governor climbs the ladder after each healthy stretch — the
    path the reactive backoff never had."""
    run_dir = str(tmp_path / "run")
    runner = ResilientRunner(
        _build(dt=0.0025),
        max_time=0.4,
        save_intervall=0.02,
        run_dir=run_dir,
        checkpoint_every_s=None,
        stability=StabilityConfig(dt_max=0.01, grow_after=2),
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    assert summary["dt"] > 0.0025  # climbed at least one rung
    grow = [
        e
        for e in _events(run_dir)
        if e["event"] == "dt_adjust" and "healthy" in e.get("reason", "")
    ]
    assert len(grow) >= 1
    assert summary["health"]["dt_max_seen"] > 0.0025


# -- reactive-path satellites -------------------------------------------------


def test_spike_fault_spec():
    plan = FaultPlan.from_spec("spike@7")
    assert (plan.kind, plan.step, plan.fired) == ("spike", 7, False)
    with pytest.raises(ValueError, match="spike"):
        FaultPlan.from_spec("warp@7")


def test_dt_min_floors_reactive_backoff_and_error_has_trajectory(tmp_path):
    """The compounding divergence backoff stops at the dt_min floor, and a
    retries-exhausted DivergenceError reports the journaled dt trajectory."""
    run_dir = str(tmp_path / "run")

    class AlwaysDiverges(ResilientRunner):
        def _rollback(self):
            super()._rollback()
            self.fault = FaultPlan.from_spec(f"nan@{self.step + 4}")

    runner = AlwaysDiverges(
        _build(),
        max_time=0.5,
        save_intervall=0.05,
        run_dir=run_dir,
        checkpoint_every_s=None,
        max_retries=3,
        dt_backoff=0.5,
        dt_min=0.004,
        fault="nan@4",
    )
    with pytest.raises(DivergenceError, match="dt trajectory") as err:
        runner.run()
    # 0.01 -> 0.005 -> floor 0.004 -> stays 0.004 (no denormal death spiral)
    assert runner.pde.get_dt() == pytest.approx(0.004)
    assert "retry" in str(err.value)
    retries = [e for e in _events(run_dir) if e["event"] == "retry"]
    assert [e["dt"] for e in retries] == pytest.approx([0.005, 0.004, 0.004])
    assert retries[-1]["dt_floor"] is True


@pytest.mark.slow
def test_governed_config_roundtrip(tmp_path):
    """StabilityConfig flows through NavierConfig/ResilienceConfig +
    from_config (as the dataclass, not an asdict casualty) and the governed
    runner works end to end off configs alone."""
    scfg = StabilityConfig(grow_after=2)
    rcfg = ResilienceConfig(
        run_dir=str(tmp_path / "run"),
        checkpoint_every_s=None,
        max_retries=1,
        respawn_seed=7,
        dt_min=1e-4,
        stability=scfg,
    )
    cfg = NavierConfig(nx=17, ny=17, ra=1e4, dt=0.01, resilience=rcfg, stability=scfg)
    model = Navier2D.from_config(cfg)
    assert model._stability is scfg  # armed at construction
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.write_intervall = 1e9
    runner = ResilientRunner.from_config(
        model, cfg.resilience, max_time=0.1, save_intervall=0.05
    )
    assert runner.stability is scfg
    assert runner.dt_min == 1e-4 and runner.respawn_seed == 7
    summary = runner.run()
    assert summary["outcome"] == "done"
    assert summary["health"] is not None
    events = [e["event"] for e in _events(str(tmp_path / "run"))]
    assert "cfl" in events and "run_health" in events


# -- integrate save-window robustness (satellite) ------------------------------


class _FakePde:
    """Minimal Integrate implementer at a huge start time: exercises the
    absolute-boundary save-window test where the legacy ``t % save`` form
    has lost the float resolution for a half-dt window."""

    def __init__(self, t0, dt, chunked):
        self.time, self.dt = t0, dt
        self.calls = []
        if chunked:
            self.update_n = self._update_n

    def _update_n(self, n):
        self.time += n * self.dt

    def update(self):
        self.time += self.dt

    def get_time(self):
        return self.time

    def get_dt(self):
        return self.dt

    def callback(self):
        self.calls.append(self.time)

    def exit(self):
        return False


@pytest.mark.parametrize("chunked", [True, False])
def test_save_window_robust_at_large_t(chunked):
    t0 = 1_048_576.0  # 2^20: ulp territory where modulo windows get noisy
    pde = _FakePde(t0, dt=1e-3, chunked=chunked)
    status = integrate(pde, t0 + 1.0, save_intervall=0.1)
    assert status == "time_limit"
    # one callback per boundary, each within a half-dt of k*0.1
    assert len(pde.calls) == 10
    for t in pde.calls:
        k = round(t / 0.1)
        assert abs(t - k * 0.1) < pde.dt / 2.0
