"""Overlapped-I/O pipeline tests (utils/io_pipeline.py + the host-snapshot
split in utils/checkpoint.py + the overlapped driver in utils/integrate.py):
write-side digests, async==sync bit-identity, future semantics, lagged break
checks, and the resilient runner's async checkpoint path."""

import json
import os

import numpy as np
import pytest

from rustpde_mpi_tpu import (
    AsyncWriteError,
    IOPipeline,
    NavierEnsemble,
    ResilientRunner,
    integrate,
)
from rustpde_mpi_tpu.config import IOConfig
from rustpde_mpi_tpu.utils import checkpoint as cp
from rustpde_mpi_tpu.utils.io_pipeline import AsyncCheckpointWriter
from rustpde_mpi_tpu.utils.resilience import poison_state

h5py = pytest.importorskip("h5py")


# shared tier-wide builder (model_builders.py) + session-scoped stepped
# model (conftest.stepped_rbc17): same jit shapes as test_resilience etc.
from model_builders import build_rbc17 as _build


def _events(run_dir):
    with open(os.path.join(run_dir, "journal.jsonl"), encoding="utf-8") as fh:
        return [json.loads(line) for line in fh]


# -- write-side digest + host-snapshot split ---------------------------------


def test_write_side_digest_matches_readback(tmp_path, stepped_rbc17):
    """The digest stamped from the in-memory arrays (no file read-back) must
    equal the digest a reader computes from the file — the contract the
    whole verify/corrupt-skip machinery rides on."""
    path = str(tmp_path / "snap.h5")
    cp.write_snapshot(stepped_rbc17, path, step=4)
    attrs = cp.verify_snapshot(path)  # raises on any digest mismatch
    with h5py.File(path, "r") as h5:
        assert attrs["digest"] == cp.content_digest(h5)


def test_ensemble_write_side_digest_and_dtypes(tmp_path):
    """Ensemble snapshots carry exact-dtype bookkeeping datasets; the
    write-side digest must cover them identically to the read-back pass."""
    ens = NavierEnsemble.from_seeds(_build(), [0, 1])
    ens.update_n(2)
    path = str(tmp_path / "ens.h5")
    cp.write_ensemble_snapshot(ens, path, step=2)
    attrs = cp.verify_snapshot(path)
    with h5py.File(path, "r") as h5:
        assert attrs["digest"] == cp.content_digest(h5)
        assert h5["members"].dtype == np.int64
        assert h5["alive"].dtype == np.int8
        assert h5["steps_done"].dtype == np.int64
    ens2 = NavierEnsemble.from_seeds(_build(), [7])
    ens2.read(path)
    assert ens2.k == 2
    for name in ("temp", "velx", "vely", "pres"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ens.state, name)), np.asarray(getattr(ens2.state, name))
        )


def test_async_write_bit_identical_to_sync(tmp_path, stepped_rbc17):
    """A host snapshot serialized on the background worker must be byte-level
    the file the synchronous writer produces (same content digest)."""
    sync_path = str(tmp_path / "sync.h5")
    async_path = str(tmp_path / "async.h5")
    cp.write_snapshot(stepped_rbc17, sync_path, step=4)
    snap = cp.snapshot_to_host(stepped_rbc17, step=4)
    pipe = IOPipeline()
    pipe.submit_write(lambda: cp.write_host_snapshot(snap, async_path), async_path)
    pipe.drain()
    pipe.close()
    assert (
        cp.verify_snapshot(sync_path)["digest"]
        == cp.verify_snapshot(async_path)["digest"]
    )


# -- futures ------------------------------------------------------------------


def test_observable_future_matches_sync(stepped_rbc17):
    fut = stepped_rbc17.get_observables_async()
    vals = stepped_rbc17.get_observables()  # resolves through the same future
    assert fut.ready()
    assert fut.result() == vals
    assert len(vals) == 4 and all(isinstance(v, float) for v in vals)
    assert not stepped_rbc17.exit_future().result()


def test_exit_future_detects_nan():
    model = _build()
    model.update_n(2)
    poison_state(model)
    assert model.exit_future().result() is True
    assert model.exit()  # the sync criterion agrees


def test_ensemble_exit_future_all_dead():
    ens = NavierEnsemble.from_seeds(_build(), [0, 1])
    ens.update_n(1)
    assert ens.exit_future().result() is False
    poison_state(ens)  # poisons every member and re-derives the mask
    ens.update_n(1)
    assert ens.exit_future().result() is True


def test_async_writer_error_surfaces_then_clears():
    writer = AsyncCheckpointWriter()

    def boom():
        raise OSError("disk gone")

    writer.submit(boom, "/tmp/doomed.h5")
    with pytest.raises(AsyncWriteError, match="doomed"):
        writer.drain()
    # the failure was observed: the writer accepts (and completes) new work
    ran = []
    writer.submit(lambda: ran.append(1), "ok")
    writer.drain()
    assert ran == [1]
    writer.close()


def test_async_writer_timeout_surfaces_wedged_write():
    """An armed ``timeout_s`` converts a wedged write (disk/NFS stuck in
    fsync) into a typed AsyncWriteError at the next back-pressure submit
    and at drain, instead of blocking the campaign silently; close()
    abandons the wedged daemon worker rather than joining forever."""
    import threading

    release = threading.Event()
    writer = AsyncCheckpointWriter(depth=1, timeout_s=0.2)
    writer.submit(release.wait, "/tmp/wedged.h5")  # occupies the one slot
    with pytest.raises(AsyncWriteError, match="back-pressure"):
        writer.submit(lambda: None, "/tmp/next.h5")
    with pytest.raises(AsyncWriteError, match="drain"):
        writer.drain()
    writer.close()  # must return promptly despite the stuck worker
    release.set()  # let the daemon thread finish


def test_diag_lag_queue_is_fifo_and_flushes():
    pipe = IOPipeline(diag_lag=1)

    class Manual:
        def __init__(self, value):
            self.value = value
            self._ready = False

        def ready(self):
            return self._ready

        def result(self):
            return self.value

    out = []
    futs = [Manual(i) for i in range(3)]
    for f in futs:
        pipe.push_diag(out.append, f)
    # one young unresolved entry may pend; older ones were forced in order
    assert out == [0, 1]
    futs[2]._ready = True
    pipe.flush_diags()
    assert out == [0, 1, 2]
    pipe.close()


# -- the overlapped driver ----------------------------------------------------


def test_overlapped_integrate_bit_identical():
    """Dispatch double-buffering reorders IO, never physics: the overlapped
    run's final state equals the blocking run's bit for bit."""
    a, b = _build(), _build()
    sa = integrate(a, 0.2, 0.05)
    sb = integrate(b, 0.2, 0.05, overlap=True)
    assert sa == sb == "time_limit"
    for x, y in zip(a.state, b.state):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_overlapped_integrate_reports_break_on_nan():
    """A NaN state must still end the run with "break" under overlap — at
    most one chunk late, and exactly at the horizon (the final state is
    always resolved before a time_limit return)."""
    model = _build()
    model.update_n(2)
    poison_state(model)
    assert integrate(model, 0.2, 0.05, overlap=True) == "break"


# -- the resilient runner's async path ---------------------------------------


def test_runner_async_matches_blocking(tmp_path):
    """Default IOConfig (async cadence checkpoints + overlap) against
    IOConfig.blocking(): same outcome, bit-equal Nu and state, final
    checkpoints byte-identical, and the journal records async cadence
    checkpoints with the step they snapshot."""
    run_a = str(tmp_path / "async")
    run_b = str(tmp_path / "block")
    ra = ResilientRunner(
        _build(), 0.3, 0.05, run_dir=run_a,
        checkpoint_every_s=None, checkpoint_every_t=0.1,
    )
    sa = ra.run()
    rb = ResilientRunner(
        _build(), 0.3, 0.05, run_dir=run_b,
        checkpoint_every_s=None, checkpoint_every_t=0.1,
        io=IOConfig.blocking(),
    )
    sb = rb.run()
    assert sa["outcome"] == sb["outcome"] == "done"
    assert sa["nu"] == sb["nu"]
    for x, y in zip(ra.pde.state, rb.pde.state):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert (
        cp.verify_snapshot(sa["checkpoint"])["digest"]
        == cp.verify_snapshot(sb["checkpoint"])["digest"]
    )
    async_ckpts = [
        e for e in _events(run_a) if e["event"] == "checkpoint" and e.get("async")
    ]
    assert async_ckpts, "no async checkpoints journaled"
    assert all("write_s" in e and "snapshot_s" in e for e in async_ckpts)
    assert sa["io"]["writes"] >= len(async_ckpts)
    assert sb["io"] is None


def test_runner_async_rollback_after_nan(tmp_path):
    """Divergence recovery under the overlapped pipeline: the writer drains
    before the rollback read, so the retry restores a settled, digest-valid
    checkpoint and completes like the synchronous harness."""
    run_dir = str(tmp_path / "nan")
    runner = ResilientRunner(
        _build(), 0.3, 0.05, run_dir=run_dir,
        checkpoint_every_s=None, max_retries=1, dt_backoff=0.5,
        fault="nan@15",
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    assert summary["retries"] == 1
    assert summary["dt"] == pytest.approx(0.005)
    assert np.isfinite(summary["nu"])
    events = [e["event"] for e in _events(run_dir)]
    assert "divergence" in events and "retry" in events
    assert events[-1] == "done"


def test_runner_async_write_failure_raises(tmp_path, monkeypatch):
    """A background cadence-write failure must stop the campaign at the
    next submission — not be silently dropped — and leave a
    ``checkpoint_failed`` journal line."""
    run_dir = str(tmp_path / "failing")
    calls = {"n": 0}
    real = cp.write_host_snapshot

    def flaky(snap, filename):
        calls["n"] += 1
        if calls["n"] >= 2:  # the anchor write succeeds, cadence writes die
            raise OSError("disk gone")
        real(snap, filename)

    monkeypatch.setattr(cp, "write_host_snapshot", flaky)
    runner = ResilientRunner(
        _build(), 0.4, 0.05, run_dir=run_dir,
        checkpoint_every_s=None, checkpoint_every_t=0.05,
    )
    with pytest.raises(AsyncWriteError, match="disk gone"):
        runner.run()
    assert any(e["event"] == "checkpoint_failed" for e in _events(run_dir))


def test_callback_pipeline_lags_then_flushes(tmp_path, monkeypatch):
    """With an attached pipeline the callback's diagnostics are emitted
    lazily but completely: after the run every boundary's row is in
    info.txt and the in-memory diagnostics map, in chronological order."""
    monkeypatch.chdir(tmp_path)
    model = _build()
    pipe = IOPipeline()
    model.io_pipeline = pipe
    try:
        integrate(model, 0.2, 0.05, overlap=True)
        pipe.drain()
    finally:
        model.io_pipeline = None
        pipe.close()
    times = model.diagnostics["time"]
    assert times == sorted(times) and len(times) == 4
    with open("data/info.txt", encoding="utf-8") as fh:
        rows = [line.split()[0] for line in fh if line.strip()]
    assert [float(r) for r in rows] == pytest.approx(times)


# -- crash consistency + governed lag=1 (ISSUE 4 satellites) ------------------

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_async_writer_kill_mid_background_write(tmp_path):
    """SIGKILL-equivalent death while the BACKGROUND worker is mid-write
    (the overlapped extension of the PR-2 mid-write kill test): the newest
    checkpoint that fully landed is digest-clean, ``latest_checkpoint``
    picks it, the half-written victim leaves at most a ``.tmp`` corpse the
    listing ignores, and a fresh runner resumes from it to completion."""
    import subprocess
    import sys

    run_dir = str(tmp_path / "killed")
    child = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["RUSTPDE_X64"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu import Navier2D, ResilientRunner
from rustpde_mpi_tpu.utils import checkpoint as cp

calls = {{"snap": 0, "arr": 0}}
orig_whs = cp.write_host_snapshot
orig_wa = cp._write_array

def wa(group, name, data):
    calls["arr"] += 1
    if calls["snap"] >= 3 and calls["arr"] >= 3:
        os._exit(9)                # die mid-write, before os.replace
    orig_wa(group, name, data)

def whs(snap, filename):
    calls["snap"] += 1
    calls["arr"] = 0
    orig_whs(snap, filename)

cp._write_array = wa
cp.write_host_snapshot = whs

m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
m.set_velocity(0.1, 1.0, 1.0); m.set_temperature(0.1, 1.0, 1.0)
m.write_intervall = 1e9
ResilientRunner(
    m, 0.3, 0.05, run_dir=sys.argv[1],
    checkpoint_every_s=None, checkpoint_every_t=0.05,
).run()                            # anchor + cadence1 land; cadence2 bombs
os._exit(1)                        # unreachable if the kill fired
""".format(repo=_REPO)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", child, run_dir],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 9, proc.stderr
    latest = cp.latest_checkpoint(run_dir)
    assert latest is not None
    attrs = cp.verify_snapshot(latest)  # digest-clean
    assert int(attrs["step"]) > 0  # a cadence checkpoint, not just the anchor
    # the half-written victim is not in the listing
    assert all(not f.endswith(".tmp") for f in cp.checkpoint_files(run_dir))
    # a fresh campaign on the same run_dir resumes from it and finishes
    runner = ResilientRunner(
        _build(), 0.3, 0.05, run_dir=run_dir,
        checkpoint_every_s=None, checkpoint_every_t=0.05,
    )
    summary = runner.run()
    assert summary["outcome"] == "done"
    assert np.isfinite(summary["nu"])
    assert any(e["event"] == "resumed" for e in _events(run_dir))


def test_rollback_read_never_races_pending_write(tmp_path, monkeypatch):
    """commit() ordering: a rollback/resume read drains the writer first,
    so picking a checkpoint while a background write is in flight returns
    the SETTLED file — never a half-written one."""
    import threading
    import time as _t

    run_dir = str(tmp_path / "race")
    os.makedirs(run_dir, exist_ok=True)
    in_write = threading.Event()
    real = cp.write_host_snapshot

    def slow(snap, filename):
        in_write.set()
        _t.sleep(0.5)
        real(snap, filename)

    monkeypatch.setattr(cp, "write_host_snapshot", slow)
    runner = ResilientRunner(
        _build(), 1.0, 0.05, run_dir=run_dir,
        checkpoint_every_s=None, checkpoint_every_t=0.05,
    )
    runner._setup_io()
    try:
        runner.pde.update_n(2)
        runner.step = 2
        path = runner._checkpoint("cadence")  # background submit
        assert in_write.wait(5.0)  # the worker is inside the slow write
        picked = runner._pick_checkpoint()  # must drain, then scan
        assert picked == path
        cp.verify_snapshot(picked)  # fully landed, digest-clean
    finally:
        runner._teardown_io()


@pytest.mark.slow
def test_governed_overlap_matches_blocking_and_catches_spike(tmp_path):
    """The lag=1 sentinel contract: a GOVERNED overlapped run at a stable
    dt is bit-identical to the blocking governed run, and a governed
    overlapped run through a velocity spike still catches it pre-NaN with
    ZERO reactive checkpoint rollbacks; the run-end journal carries the
    ``io_overlap`` summary."""
    from rustpde_mpi_tpu.config import StabilityConfig

    def governed(run_dir, io, fault=None):
        return ResilientRunner(
            _build(), 0.3, 0.05, run_dir=run_dir,
            checkpoint_every_s=None, checkpoint_every_t=0.1,
            max_retries=2, stability=StabilityConfig(),
            fault=fault, spike_factor=200.0, io=io,
        )

    ra = governed(str(tmp_path / "lag1"), IOConfig())
    sa = ra.run()
    rb = governed(str(tmp_path / "block"), IOConfig.blocking())
    sb = rb.run()
    assert sa["outcome"] == sb["outcome"] == "done"
    assert sa["nu"] == sb["nu"]  # bit-identical under reordering
    for x, y in zip(ra.pde.state, rb.pde.state):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    overlap_ev = [e for e in _events(str(tmp_path / "lag1"))
                  if e["event"] == "io_overlap"]
    assert overlap_ev and overlap_ev[0]["bytes"] > 0
    assert overlap_ev[0]["queue_depth"] == 1

    spike_dir = str(tmp_path / "spike")
    ss = governed(spike_dir, IOConfig(), fault="spike@10").run()
    assert ss["outcome"] == "done"
    assert ss["retries"] == 0  # caught pre-NaN: no reactive rollback
    assert np.isfinite(ss["nu"])
    events = [e["event"] for e in _events(spike_dir)]
    assert "pre_divergence" in events and "dt_adjust" in events
    assert "divergence" not in events and "retry" not in events
    assert ss["health"]["pre_divergence_catches"] >= 1
