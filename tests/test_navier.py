"""Physics-level tests for the Navier2D model.

Mirrors the reference's observational validation strategy (SURVEY.md S4):
divergence-free projection, decay to the conduction state below the critical
Rayleigh number, convective instability above it, and the periodic
(Fourier x Chebyshev) configuration.
"""

import numpy as np
import pytest

from rustpde_mpi_tpu import Navier2D


def test_step_runs_and_is_finite():
    model = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc")
    model.update()
    for arr in model.state:
        assert np.all(np.isfinite(np.asarray(arr)))
    assert model.get_time() == pytest.approx(0.01)


def test_projection_controls_divergence():
    # incremental pressure correction: the divergence error is O(dt) per step
    # and shrinks as the accumulated pressure converges
    model = Navier2D(25, 25, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.update_n(20)
    div_early = model.div_norm()
    model.update_n(180)
    # measured ~1.2e-4 at t=2 under the truncated-B2 (reference-exact)
    # discretization (was ~1e-4 before it); decays to ~2e-5 by t=6
    assert model.div_norm() < 2e-4
    assert model.div_norm() < 0.5 * div_early


def test_subcritical_decay_to_conduction():
    # Ra = 100 << Ra_c ~ 1708: any disturbance decays; Nu -> 1 (pure conduction)
    model = Navier2D.new_confined(17, 17, 100.0, 1.0, 0.05, 1.0, "rbc")
    re_start = model.eval_re()
    model.update_n(400)
    assert model.eval_re() < 0.05 * max(re_start, 1e-10)
    assert model.eval_nu() == pytest.approx(1.0, abs=1e-3)
    assert model.eval_nuvol() == pytest.approx(1.0, abs=1e-3)


def test_supercritical_convection_grows():
    # Ra = 1e5 >> Ra_c: kinetic energy must grow from a small smooth seed
    model = Navier2D(33, 33, 1e5, 1.0, 0.005, 1.0, "rbc", periodic=False)
    model.set_velocity(0.01, 1.0, 1.0)
    model.set_temperature(0.01, 1.0, 1.0)
    model.update_n(100)
    re_early = model.eval_re()
    model.update_n(500)
    assert model.eval_re() > 2.0 * re_early


def test_conduction_state_is_near_fixed_point():
    # zero IC: temp stays zero (lift field carries the linear profile, whose
    # laplacian vanishes); velocity stays small once pressure absorbs buoyancy
    model = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    model.update_n(200)
    assert np.abs(np.asarray(model.state.temp)).max() < 1e-5
    assert np.abs(model.get_field("vely")).max() < 1e-4
    assert model.eval_nu() == pytest.approx(1.0, abs=1e-4)


def test_hc_boundary_condition_runs():
    model = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "hc")
    model.update_n(10)
    for arr in model.state:
        assert np.all(np.isfinite(np.asarray(arr)))


def test_periodic_hc_runs_and_convects():
    """Horizontally-periodic horizontal convection (the reference's
    navier_periodic_hc_mpi example config): the cos-bottom heating drives a
    finite circulation."""
    model = Navier2D.new_periodic(16, 17, 1e5, 1.0, 0.01, 1.0, "hc")
    model.set_velocity(0.2, 1.0, 1.0)
    model.set_temperature(0.2, 1.0, 1.0)
    model.update_n(100)
    nu, nuvol, re, div = model.get_observables()
    assert np.isfinite([nu, nuvol, re, div]).all()
    assert re > 0.1  # flow actually moves
    assert div < 1e-1


def test_periodic_model_runs_divergence_controlled():
    model = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
    model.set_velocity(0.1, 1.0, 1.0)
    model.set_temperature(0.1, 1.0, 1.0)
    model.update_n(100)
    assert np.iscomplexobj(np.asarray(model.state.temp))
    assert model.div_norm() < 5e-3
    for arr in model.state:
        assert np.all(np.isfinite(np.asarray(arr)))


def test_periodic_subcritical_decay():
    model = Navier2D.new_periodic(16, 17, 100.0, 1.0, 0.05, 1.0, "rbc")
    model.update_n(400)
    # subcritical: convection decays to the conduction state, Nu -> 1.
    # (The reference's periodic-axis weights sum to n/(n-1) so its periodic Nu
    # carries a resolution-dependent factor, /root/reference/src/field.rs:139-141
    # + field/average.rs:28-35; this repo deliberately normalizes over the full
    # period — see field._axis_length — so Nu is exactly 1 here.)
    assert model.eval_nu() == pytest.approx(1.0, abs=1e-3)


def test_exit_is_false_for_healthy_run():
    model = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc")
    model.update()
    assert model.exit() is False


def test_nan_divergence_early_exit_in_chunk():
    """In-chunk failure detection (reference: per-step ``pde.exit()``,
    /root/reference/src/lib.rs:187-219): once the flow is NaN the scanned
    chunk stops stepping on device — the step counter threaded through the
    scan carry freezes at the first NaN step instead of burning the chunk."""
    import jax
    import jax.numpy as jnp

    model = Navier2D.new_confined(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc")

    # _step_n donates its input buffers (update_n hands it a fresh copy);
    # this private-API test must honor the same contract because it reuses
    # model.state after the call
    def dup(s):
        return jax.tree.map(jnp.copy, s)

    # healthy state: all 64 scheduled steps execute
    _, done = model._step_n(dup(model.state), 64)
    assert int(done) == 64
    # poison one temperature mode: the first step produces a NaN field, the
    # remaining 63 iterations take the identity branch
    bad = model.state._replace(
        temp=model.state.temp.at[(0,) * model.state.temp.ndim].set(jnp.nan)
    )
    frozen, done = model._step_n(dup(bad), 64)
    assert int(done) == 1
    # the driver-visible criterion fires at the next boundary
    model.state = frozen
    model._obs_cache = None
    assert model.exit() is True
