"""CI coverage for the execution paths the real TPU chip uses.

CI runs on CPU (tests/conftest.py), where the defaults are FFT transforms +
banded-scan solvers; on the axon TPU the model instead runs matmul transforms
+ DenseSolver ADI + FastDiag Poisson (no complex dtypes, no FFT).  These
tests force that path via RUSTPDE_FORCE_TPU_PATH and assert it produces the
same physics as the default path — so a TPU-only numerical bug cannot hide
behind CPU-only CI (VERDICT r1 weak #4).
"""

import numpy as np
import pytest

from rustpde_mpi_tpu import Navier2D, Space2, cheb_dirichlet, cheb_neumann
from rustpde_mpi_tpu.solver import HholtzAdi, Poisson


@pytest.fixture()
def tpu_path(monkeypatch):
    monkeypatch.setenv("RUSTPDE_FORCE_TPU_PATH", "1")
    yield
    monkeypatch.delenv("RUSTPDE_FORCE_TPU_PATH", raising=False)


def test_forced_path_selects_tpu_defaults(tpu_path):
    from rustpde_mpi_tpu import config
    from rustpde_mpi_tpu.solver import FastDiag, default_method

    assert config.is_tpu_like()
    assert default_method() == "dense"
    space = Space2(cheb_dirichlet(9), cheb_dirichlet(9))
    assert space.method == "matmul"
    solver = Poisson(space, (1.0, 1.0))
    assert isinstance(solver._solver, FastDiag)


def test_matmul_transforms_match_fft(tpu_path):
    space_tpu = Space2(cheb_dirichlet(17), cheb_neumann(17))
    assert space_tpu.method == "matmul"
    space_fft = Space2(cheb_dirichlet(17), cheb_neumann(17), method="fft")
    rng = np.random.default_rng(3)
    v = rng.standard_normal((17, 17))
    a = space_tpu.forward(v)
    b = np.asarray(space_fft.forward(v))
    # the TPU matmul path stores spectral axes parity-separated (ops/folded
    # sep layout); compare in the natural order via the IO-boundary helper
    np.testing.assert_allclose(space_tpu.spectral_to_natural(a), b, atol=1e-12)
    np.testing.assert_allclose(
        np.asarray(space_tpu.backward(a)), np.asarray(space_fft.backward(b)), atol=1e-12
    )


def test_model_tpu_path_matches_default_path(tpu_path, monkeypatch):
    """Full confined model: 30 steps on the forced TPU path vs the CPU
    default path — observables and fields must agree to spectral accuracy."""

    def build():
        model = Navier2D(25, 25, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        return model

    tpu_model = build()
    assert tpu_model.field_space.method == "matmul"
    monkeypatch.delenv("RUSTPDE_FORCE_TPU_PATH")
    cpu_model = build()
    assert cpu_model.field_space.method == "fft"

    tpu_model.update_n(30)
    cpu_model.update_n(30)
    spaces = ("temp_space", "velx_space", "vely_space", "pres_space", "pseu_space")
    for sp_name, a, b in zip(spaces, tpu_model.state, cpu_model.state):
        space = getattr(tpu_model, sp_name)
        np.testing.assert_allclose(
            space.spectral_to_natural(a), np.asarray(b), atol=1e-9, err_msg=sp_name
        )
    for va, vb in zip(tpu_model.get_observables(), cpu_model.get_observables()):
        assert va == pytest.approx(vb, rel=1e-8, abs=1e-10)


def test_dense_adi_matches_banded():
    space = Space2(cheb_dirichlet(33), cheb_dirichlet(33))
    rng = np.random.default_rng(7)
    rhs = rng.standard_normal((33, 33))
    x_banded = np.asarray(HholtzAdi(space, (0.1, 0.1), method="banded").solve(rhs))
    x_dense = np.asarray(HholtzAdi(space, (0.1, 0.1), method="dense").solve(rhs))
    np.testing.assert_allclose(x_dense, x_banded, atol=1e-11)


def test_periodic_model_tpu_split_path_matches_complex(tpu_path, monkeypatch):
    """Horizontally-periodic model on the forced TPU path (split Re/Im
    Fourier + matmul transforms) vs the CPU complex-FFT path."""

    def build():
        model = Navier2D(16, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=True)
        model.set_velocity(0.1, 1.0, 1.0)
        model.set_temperature(0.1, 1.0, 1.0)
        return model

    tpu_model = build()
    assert tpu_model.temp_space.base_x.kind.is_split
    monkeypatch.delenv("RUSTPDE_FORCE_TPU_PATH")
    cpu_model = build()
    assert not cpu_model.temp_space.base_x.kind.is_split

    tpu_model.update_n(20)
    cpu_model.update_n(20)
    np.testing.assert_allclose(
        tpu_model.get_field("temp"), cpu_model.get_field("temp"), atol=1e-9
    )
    for va, vb in zip(tpu_model.get_observables(), cpu_model.get_observables()):
        assert va == pytest.approx(vb, rel=1e-8, abs=1e-10)


def test_swift_hohenberg_tpu_matmul_path(tpu_path, monkeypatch):
    """SH2D biperiodic space auto-selects matmul under the forced TPU path
    and reproduces the FFT-path trajectory."""
    from rustpde_mpi_tpu import SwiftHohenberg2D

    tpu_model = SwiftHohenberg2D(16, 16, r=0.3, dt=0.02, length=6.0)
    assert tpu_model.space.method == "matmul"
    monkeypatch.delenv("RUSTPDE_FORCE_TPU_PATH")
    cpu_model = SwiftHohenberg2D(16, 16, r=0.3, dt=0.02, length=6.0)
    assert cpu_model.space.method == "fft"
    tpu_model.update_n(50)
    cpu_model.update_n(50)
    np.testing.assert_allclose(
        tpu_model.theta_physical(), cpu_model.theta_physical(), atol=1e-10
    )


def test_penalization_tpu_path_matches_default(tpu_path, monkeypatch):
    """Brinkman penalization on the forced TPU path == default path."""
    from rustpde_mpi_tpu.models.solid_masks import solid_cylinder_inner

    def build():
        model = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
        x, y = model.x
        mask, value = solid_cylinder_inner(x, y, 0.0, 0.0, 0.3)
        model.set_solid(mask, value)
        model.set_velocity(0.1, 1.0, 1.0)
        return model

    tpu_model = build()
    monkeypatch.delenv("RUSTPDE_FORCE_TPU_PATH")
    cpu_model = build()
    tpu_model.update_n(20)
    cpu_model.update_n(20)
    np.testing.assert_allclose(
        tpu_model.get_field("velx"), cpu_model.get_field("velx"), atol=1e-10
    )


@pytest.mark.slow
def test_f64_hybrid_tracks_full_f64():
    """RUSTPDE_F64_HYBRID=1 (f32 convection transforms feeding f64 solves,
    SURVEY S7 hybrid): state stays f64 and a 50-step trajectory tracks the
    all-f64 one at f32-roundoff level.  Subprocesses: the sep-operator cache
    is keyed per-process by the build-time env."""
    import json
    import os
    import subprocess
    import sys

    code = (
        "import os, jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import json\n"
        "from rustpde_mpi_tpu import Navier2D\n"
        "m = Navier2D.new_confined(33, 33, 1e5, 1.0, 5e-3, 1.0, 'rbc')\n"
        "assert all(m.temp_space.sep)\n"
        "assert str(m.state.temp.dtype) == 'float64'\n"
        "m.set_velocity(0.1, 2.0, 2.0); m.set_temperature(0.1, 2.0, 2.0)\n"
        "m.update_n(50)\n"
        "assert str(m.state.temp.dtype) == 'float64'\n"
        "print(json.dumps(list(m.get_observables())))\n"
    )
    obs = {}
    for hybrid in ("0", "1"):
        env = dict(
            os.environ,
            RUSTPDE_X64="1",
            RUSTPDE_FORCE_TPU_PATH="1",
            RUSTPDE_F64_HYBRID=hybrid,
            JAX_PLATFORMS="cpu",
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=600,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        obs[hybrid] = json.loads(out.stdout.strip().splitlines()[-1])
    nu64, nuh = obs["0"][0], obs["1"][0]
    assert abs(nuh - nu64) / abs(nu64) < 1e-4, (obs["0"], obs["1"])
    # Re and |div| also agree; the hybrid must not degrade divergence control
    assert abs(obs["1"][2] - obs["0"][2]) / abs(obs["0"][2]) < 1e-4
    assert obs["1"][3] < 2 * max(obs["0"][3], 1e-12)


@pytest.mark.slow
def test_f64_hybrid_sharded_matches_serial():
    """The f64 hybrid under the 8-device pencil mesh == serial hybrid: the
    f32-cast convection operators must partition cleanly under GSPMD (real
    multichip would run exactly this combination)."""
    import os
    import re
    import subprocess
    import sys

    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "from rustpde_mpi_tpu import Navier2D\n"
        "from rustpde_mpi_tpu.parallel.mesh import AXIS\n"
        "def build(mesh):\n"
        "    m = Navier2D(17, 16, 1e4, 1.0, 1e-2, 1.0, 'rbc', periodic=False, mesh=mesh)\n"
        "    m.set_velocity(0.1, 1.0, 1.0)\n"
        "    m.set_temperature(0.1, 1.0, 1.0)\n"
        "    return m\n"
        "serial = build(None)\n"
        "mesh = Mesh(np.array(jax.devices()[:8]), (AXIS,))\n"
        "sharded = build(mesh)\n"
        "serial.update_n(6)\n"
        "sharded.update_n(6)\n"
        "# f32 GEMM segments reassociate differently under partitioning; the\n"
        "# agreement bar is f32 roundoff (observed ~2e-11), not bitwise\n"
        "np.testing.assert_allclose(np.asarray(sharded.state.temp),\n"
        "                           np.asarray(serial.state.temp), atol=1e-9)\n"
        "print('OK', serial.eval_nu())\n"
    )
    env = dict(
        os.environ,
        RUSTPDE_X64="1",
        RUSTPDE_FORCE_TPU_PATH="1",
        RUSTPDE_F64_HYBRID="1",
        JAX_PLATFORMS="cpu",
    )
    env["XLA_FLAGS"] = (
        re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            env.get("XLA_FLAGS", ""),
        ).strip()
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, env=env, timeout=900,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    assert "OK" in out.stdout
