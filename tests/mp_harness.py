"""Shared 2-process CPU cluster spawner for tests/mp_worker.py.

One copy of the spawn recipe (port allocation, CPU/virtual-device env,
worker argv order, sequential communicate) used by BOTH
tests/test_multiprocess.py and bench.py's ``shardedio129`` config, so the
bench harness can never drift from the tested one.  Deliberately imports
no jax: the parent (possibly TPU-bound bench process) must not have its
platform touched.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn_cluster(
    out_dir: str,
    mode: str | None = None,
    nproc: int = 2,
    env_extra: dict | None = None,
    timeout: float = 600,
    check: bool = True,
):
    """Run ``nproc`` mp_worker.py processes as one jax.distributed cluster.

    Returns ``[(returncode, stdout, stderr), ...]`` in rank order, or
    ``None`` when the spawn timed out (workers killed — callers decide
    whether that skips or fails).  ``check=True`` asserts every rank
    exited 0; pass ``check=False`` for fault-injection runs that expect
    specific nonzero codes and assert on the returned list."""
    port = free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        RUSTPDE_X64="1",
        **(env_extra or {}),
    )
    argv_tail = [out_dir] + ([mode] if mode else [])
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(_REPO, "tests", "mp_worker.py"),
                str(port),
                str(i),
                str(nproc),
                *argv_tail,
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return None
    if check:
        for rc, out, err in outs:
            assert rc == 0, f"worker failed (rc={rc}):\n{err[-3000:]}"
    return outs
