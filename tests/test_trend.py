"""Perf-trend gating (scripts/bench_trend.py): BENCH history parsing, the
noise-band regression verdict, the ack workflow, and the checked-in repo
history producing a clean TREND.json."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "bench_trend.py")


def _run(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, SCRIPT, *args],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=cwd,
    )


def _round_file(path, configs, rc=0, flagship=None):
    parsed = {"configs": configs}
    if flagship is not None:
        parsed.update(
            {"metric": "timesteps/sec", "value": flagship, "unit": "steps/s"}
        )
    with open(path, "w") as fh:
        json.dump({"n": 1, "cmd": "bench", "rc": rc, "tail": "", "parsed": parsed}, fh)


def _fake_history(tmp_path, r03_rate):
    """Three rounds of one config; r03 carries the rate under test."""
    for i, rate in enumerate([100.0, 104.0, r03_rate], start=1):
        _round_file(
            str(tmp_path / f"BENCH_r{i:02d}.json"),
            {"rbc129": {"steps_per_sec": rate, "finite": True}},
        )


def test_trend_clean_history_no_regression(tmp_path):
    _fake_history(tmp_path, r03_rate=98.0)  # within the 30% band
    out = str(tmp_path / "TREND.json")
    proc = _run(["--repo", str(tmp_path), "--out", out, "--json", "--gate"])
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["regressions"] == [] and payload["regressions_unacked"] == []
    cfg = payload["configs"]["rbc129"]
    assert cfg["rolling_best"] == 104.0 and cfg["latest"] == 98.0
    assert [p["label"] for p in cfg["points"]] == ["r01", "r02", "r03"]
    # the artifact landed
    assert json.load(open(out))["configs"]["rbc129"]["regressed"] is False


def test_trend_flags_synthetic_regression_and_ack_clears_it(tmp_path):
    _fake_history(tmp_path, r03_rate=40.0)  # 62% below the rolling best
    out = str(tmp_path / "TREND.json")
    proc = _run(["--repo", str(tmp_path), "--out", out, "--json", "--gate"])
    assert proc.returncode == 5, (proc.stdout, proc.stderr)
    payload = json.loads(proc.stdout)
    assert payload["regressions_unacked"] == ["rbc129"]
    assert payload["configs"]["rbc129"]["ratio"] < 0.7

    # an ack without a written reason is refused
    proc = _run(["--repo", str(tmp_path), "--out", out, "--ack", "rbc129"])
    assert proc.returncode == 2

    # acked with a reason: the gate passes, the ack is recorded in TREND.json
    proc = _run(
        ["--repo", str(tmp_path), "--out", out, "--json", "--gate",
         "--ack", "rbc129", "--reason", "relay slowdown, tracked upstream"]
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["regressions"] == ["rbc129"]
    assert payload["regressions_unacked"] == []
    assert payload["acks"]["rbc129"]["reason"].startswith("relay slowdown")

    # the ack persists across runs (it lives inside TREND.json)...
    proc = _run(["--repo", str(tmp_path), "--out", out, "--json", "--gate"])
    assert proc.returncode == 0

    # ... but pins (config, label, VALUE): a re-captured point at the SAME
    # label with a different (worse) value re-fires — BENCH_FULL's label
    # is always "full", so a label-only pin would silence that config
    # forever after one ack
    _round_file(
        str(tmp_path / "BENCH_r03.json"),
        {"rbc129": {"steps_per_sec": 10.0, "finite": True}},
    )
    proc = _run(["--repo", str(tmp_path), "--out", out, "--json", "--gate"])
    assert proc.returncode == 5
    assert json.loads(proc.stdout)["regressions_unacked"] == ["rbc129"]

    # restore the acked capture, then a FURTHER round regressing re-fires
    _round_file(
        str(tmp_path / "BENCH_r03.json"),
        {"rbc129": {"steps_per_sec": 40.0, "finite": True}},
    )
    _round_file(
        str(tmp_path / "BENCH_r04.json"),
        {"rbc129": {"steps_per_sec": 20.0, "finite": True}},
    )
    proc = _run(["--repo", str(tmp_path), "--out", out, "--json", "--gate"])
    assert proc.returncode == 5
    assert json.loads(proc.stdout)["regressions_unacked"] == ["rbc129"]


def test_trend_skips_unparseable_rounds_and_stale_rows(tmp_path):
    _round_file(
        str(tmp_path / "BENCH_r01.json"),
        {
            "rbc129": {"steps_per_sec": 100.0},
            "old": {"steps_per_sec": 50.0, "stale": True},
        },
    )
    # an rc!=0 round with no recoverable JSON is skipped, not fatal
    with open(str(tmp_path / "BENCH_r02.json"), "w") as fh:
        json.dump({"n": 2, "rc": 1, "tail": "Traceback ...", "parsed": None}, fh)
    proc = _run(
        ["--repo", str(tmp_path), "--out", str(tmp_path / "TREND.json"),
         "--json"]
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert "old" not in payload["configs"]  # stale rows excluded
    assert [p["label"] for p in payload["configs"]["rbc129"]["points"]] == ["r01"]


def test_trend_recovers_final_json_line_from_tail(tmp_path):
    tail = 'noise\n{"metric": "x", "value": 42.0, "unit": "steps/s"}\n'
    with open(str(tmp_path / "BENCH_r01.json"), "w") as fh:
        json.dump({"n": 1, "rc": 0, "tail": tail, "parsed": None}, fh)
    proc = _run(
        ["--repo", str(tmp_path), "--out", str(tmp_path / "TREND.json"),
         "--json"]
    )
    payload = json.loads(proc.stdout)
    assert payload["configs"]["flagship"]["latest"] == 42.0


def test_trend_real_repo_history_parses_clean(tmp_path):
    """The acceptance criterion: the checked-in BENCH_r01–r05 +
    BENCH_FULL history produces a TREND.json (written to a scratch path —
    the committed artifact is regenerated by record_tests.py)."""
    out = str(tmp_path / "TREND.json")
    proc = _run(["--json", "--out", out])
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    # the known rounds parse: the flagship trajectory spans r01/r02 and
    # BENCH_FULL contributes the per-config points
    assert "flagship" in payload["configs"]
    assert len(payload["configs"]) >= 5
    assert payload["regressions_unacked"] == []
    assert os.path.exists(out)
