"""Fleet-layer tests (rustpde_mpi_tpu/serve/fleet/): queue-level bucket
leases with fencing tokens and clock-robust staleness, the stateless
HTTP proxy tier, the QoS traffic contract (quotas / priority classes /
deadlines / preemption), durable parked continuations, the queued-dir
listing cache, and the fleet-off invariant (zero extra journal rows).

The multi-replica SIGKILL chaos soak (proxy + 2 replicas, one killed
mid-campaign while holding leases and parked continuations) lives in the
slow tier; the tier-1 tests here exercise every protocol transition at
small scale, most without any device work at all.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from rustpde_mpi_tpu import Navier2D
from rustpde_mpi_tpu.config import FleetConfig, ServeConfig
from rustpde_mpi_tpu.serve import (
    AdmissionError,
    DurableQueue,
    FleetProxy,
    LeaseLost,
    LeaseManager,
    RequestError,
    SimRequest,
    SimServer,
)
from rustpde_mpi_tpu.serve.fleet import qos
from rustpde_mpi_tpu.serve.fleet.lease import bucket_tag
from rustpde_mpi_tpu.utils import checkpoint
from rustpde_mpi_tpu.utils.journal import read_journal

h5py = pytest.importorskip("h5py")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the shared tier shapes (tests/model_builders.py): 17^2 rbc, dt=0.01
_REQ = dict(ra=1e4, pr=1.0, nx=17, ny=17, dt=0.01, horizon=0.1, bc="rbc")
_KEY = SimRequest(**_REQ).compat_key


def _cfg(tmp_path, **kw):
    kw.setdefault("run_dir", str(tmp_path / "fleet"))
    kw.setdefault("slots", 2)
    kw.setdefault("chunk_steps", 4)
    kw.setdefault("checkpoint_every_s", None)
    kw.setdefault("http_port", None)
    return ServeConfig(**kw)


def _replica_events(run_dir, rid):
    return read_journal(
        os.path.join(run_dir, "replicas", rid, "journal.jsonl")
    )


# -- lease protocol (no jax, no server) ---------------------------------------


def test_lease_claim_renew_release_and_tokens(tmp_path):
    root = str(tmp_path / "leases")
    m1 = LeaseManager(root, "r1", ttl_s=60.0)
    m2 = LeaseManager(root, "r2", ttl_s=60.0)
    lease = m1.claim(_KEY)
    assert lease is not None and lease.token == 1
    assert lease.tag == bucket_tag(_KEY)
    # held: a second replica cannot claim (sweep's business, not claim's)
    assert m2.claim(_KEY) is None
    lease.renew()
    lease.guard()
    # clean release escrows the token; the next claim is strictly newer
    lease.release()
    lease2 = m2.claim(_KEY)
    assert lease2 is not None and lease2.token == 2
    # the released holder is fenced on every surface
    with pytest.raises(LeaseLost):
        lease.guard()
    with pytest.raises(LeaseLost):
        lease.renew()


def test_lease_claim_race_exactly_one_winner(tmp_path):
    """Two replicas race one bucket's lease file concurrently, many
    rounds: exactly one claim succeeds per round (the exclusive-dirent
    protocol's whole point)."""
    root = str(tmp_path / "leases")
    mgrs = [LeaseManager(root, f"r{i}", ttl_s=60.0) for i in range(4)]
    for _ in range(10):
        wins, barrier = [], threading.Barrier(len(mgrs))

        def race(m):
            barrier.wait()
            lease = m.claim(_KEY)
            if lease is not None:
                wins.append(lease)

        threads = [threading.Thread(target=race, args=(m,)) for m in mgrs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1, [w.owner for w in wins]
        wins[0].release()


def test_lease_stale_break_and_fencing(tmp_path):
    """break-then-reclaim ordering: a stale lease is broken by exactly one
    survivor, the re-claim gets a strictly greater fencing token, and the
    stale holder's writes are rejected from then on."""
    root = str(tmp_path / "leases")
    m1 = LeaseManager(root, "dead", ttl_s=0.1)
    m2 = LeaseManager(root, "live", ttl_s=0.1)
    m3 = LeaseManager(root, "late", ttl_s=0.1)
    lease = m1.claim(_KEY)
    assert lease.token == 1
    # observer-monotonic staleness: first observation opens a full TTL
    assert m2.stale(lease.tag) is False
    time.sleep(0.15)
    assert m2.stale(lease.tag) is True
    # two survivors race the break: the rename's vanishing source lets
    # exactly one through
    assert m3.stale(lease.tag) is False  # late observer: fresh window
    broken = m2.break_lease(lease.tag)
    assert broken is not None and broken["owner"] == "dead"
    assert m2.break_lease(lease.tag) is None  # raced: source is gone
    relcaim = m2.claim(_KEY)
    assert relcaim.token == 2  # strictly past every token ever issued
    # the stale holder is FENCED: renew and guard both reject
    with pytest.raises(LeaseLost):
        lease.renew()
    with pytest.raises(LeaseLost):
        lease.guard()


def test_lease_clock_skew_grants_extra_ttl(tmp_path):
    """The NTP-step satellite: a heartbeat mtime that jumps BACKWARDS is
    a clock artifact, not a death — the lease reads live for one extra
    TTL instead of being instantly broken."""
    root = str(tmp_path / "leases")
    holder = LeaseManager(root, "h", ttl_s=0.2)
    watcher = LeaseManager(root, "w", ttl_s=0.2)
    lease = holder.claim(_KEY)
    assert watcher.stale(lease.tag) is False  # first sight: window opens
    time.sleep(0.25)
    # an NTP step: the lease file's mtime moves BACKWARDS with no renew
    past = time.time() - 3600.0
    os.utime(lease.path, (past, past))
    # the change restarts the observation window — live for one more TTL
    assert watcher.stale(lease.tag) is False
    time.sleep(0.25)
    # no further change for a full TTL: NOW it is genuinely stale
    assert watcher.stale(lease.tag) is True
    assert watcher.sweep()[0]["owner"] == "h"


def test_lease_resurrection_after_break_is_retracted(tmp_path):
    """The guard-then-write race: a zombie holder whose write lands AFTER
    a survivor broke its lease must stand down at its next renewal (the
    token escrow moved to its token) and RETRACT the resurrected record —
    never fence the legitimate new owner."""
    root = str(tmp_path / "leases")
    zombie_mgr = LeaseManager(root, "zombie", ttl_s=0.1)
    survivor = LeaseManager(root, "survivor", ttl_s=0.1)
    zombie = zombie_mgr.claim(_KEY)
    survivor.stale(zombie.tag)
    time.sleep(0.15)
    assert survivor.break_lease(zombie.tag) is not None
    # the zombie's stalled write lands now, resurrecting its record over
    # the broken lease (simulated: rewrite its pre-break record)
    with open(zombie.path, "w", encoding="utf-8") as fh:
        json.dump(zombie_mgr._record(zombie, 1), fh)
    # the zombie's next heartbeat hits the escrow fence and retracts
    with pytest.raises(LeaseLost, match="escrow"):
        zombie.renew()
    assert not os.path.exists(zombie.path)
    # the bucket is immediately claimable with a strictly newer token
    lease2 = survivor.claim(_KEY)
    assert lease2 is not None and lease2.token == 2
    lease2.guard()


def test_lease_break_crash_intermediate_is_adopted(tmp_path):
    """A breaker that dies between the break rename and the escrow write
    leaves a ``.breaking.`` intermediate; the next claim adopts its token
    so fencing monotonicity survives the breaker's crash."""
    root = str(tmp_path / "leases")
    m1 = LeaseManager(root, "r1", ttl_s=60.0)
    lease = m1.claim(_KEY)
    # simulate the crashed breaker: rename away, never escrow
    os.replace(lease.path, lease.path + ".breaking.crashed.1")
    m2 = LeaseManager(root, "r2", ttl_s=60.0)
    lease2 = m2.claim(_KEY)
    assert lease2 is not None and lease2.token == 2


def test_lease_heartbeat_carries_monotonic_epoch_pair(tmp_path):
    lease = LeaseManager(str(tmp_path), "r1", ttl_s=60.0).claim(_KEY)
    with open(lease.path, encoding="utf-8") as fh:
        rec = json.load(fh)
    assert {"owner", "token", "seq", "hb_unix", "hb_mono", "bucket"} <= set(rec)
    lease.renew()
    with open(lease.path, encoding="utf-8") as fh:
        rec2 = json.load(fh)
    assert rec2["seq"] == rec["seq"] + 1
    assert rec2["hb_mono"] >= rec["hb_mono"]


# -- QoS policy (pure host-side) ----------------------------------------------


def test_qos_priority_validation_and_ranks():
    req = SimRequest(**_REQ, priority="interactive", deadline_s=10.0)
    req.validate()
    assert req.class_rank == 0
    assert SimRequest(**_REQ).class_rank == 1  # default: batch
    assert SimRequest(**_REQ, priority="best-effort").class_rank == 2
    with pytest.raises(RequestError, match="priority"):
        SimRequest(**_REQ, priority="urgent").validate()
    with pytest.raises(RequestError, match="deadline"):
        SimRequest(**_REQ, deadline_s=-1.0).validate()
    with pytest.raises(RequestError, match="tenant"):
        SimRequest(**_REQ, tenant="").validate()
    # tenant/priority/deadline never join the bucket key: classes co-batch
    assert SimRequest(**_REQ, priority="interactive", tenant="a").compat_key == _KEY


def test_qos_bucket_order_and_at_risk():
    now = time.time()
    mk = lambda i, **kw: (f"{i:020d}-x.json", SimRequest(**dict(_REQ, **kw)))
    be = mk(1, dt=0.01, priority="best-effort")
    ia = mk(2, dt=0.005, priority="interactive", deadline_s=60.0)
    ba = mk(3, dt=0.0025)
    order = qos.bucket_order([be, ia, ba], now)
    assert order[0] == ia[1].compat_key  # class before arrival
    assert order[1] == ba[1].compat_key  # batch before best-effort
    # deadline slack breaks ties inside a class
    tight = mk(4, dt=0.02, priority="interactive", deadline_s=1.0)
    assert qos.bucket_order([ia, tight], now)[0] == tight[1].compat_key
    # at-risk: only deadline-carrying requests under the slack threshold
    assert qos.find_at_risk([be, ba], 30.0, now) is None
    assert qos.find_at_risk([ia], 30.0, now) is None  # 60s slack > 30s
    assert qos.find_at_risk([tight], 30.0, now).id == tight[1].id


def test_qos_preempt_victims_class_rules():
    at_risk = SimRequest(**_REQ, priority="interactive", deadline_s=1.0)
    be1 = SimRequest(**_REQ, priority="best-effort")
    be2 = SimRequest(**_REQ, priority="best-effort")
    batch = SimRequest(**_REQ)
    running = [(0, be1), (1, batch), (2, be2)]
    # same bucket: exactly ONE lane frees (the at-risk refills it)
    assert len(qos.preempt_victims(running, at_risk, _KEY)) == 1
    # cross-bucket: every best-effort lane parks, batch is NEVER a victim
    other = ("other",) + _KEY[1:]
    victims = qos.preempt_victims(running, at_risk, other)
    assert sorted(victims) == [0, 2]
    # batch emergencies preempt nothing
    assert qos.preempt_victims(running, SimRequest(**_REQ, deadline_s=1.0), other) == []


def test_qos_quota_check():
    fleet = FleetConfig(default_quota=2, quotas={"vip": None})
    req = SimRequest(**_REQ, tenant="t1")
    qos.check_quota(req, {"t1": 1}, fleet)  # under quota: fine
    with pytest.raises(AdmissionError) as exc:
        qos.check_quota(req, {"t1": 2}, fleet)
    assert exc.value.reason == "quota" and exc.value.retry_after_s > 0
    # per-tenant override: vip is unlimited
    qos.check_quota(SimRequest(**_REQ, tenant="vip"), {"vip": 99}, fleet)


# -- queued-dir listing cache (satellite) -------------------------------------


def test_queue_listing_cache_bounds_listdir_per_boundary(tmp_path, monkeypatch):
    """The O(all files) regression gate: after warmup, one scheduler
    boundary's worth of queue consults (bucket order, counts-by-bucket,
    fairness probe, a claim) costs ZERO queued-dir listdirs — the cache
    absorbs them and stays coherent across enqueue/claim/requeue."""
    q = DurableQueue(str(tmp_path / "q"), max_queue=64)
    for s in range(12):
        q.submit(SimRequest(**_REQ, seed=s))
    calls = {"queued": 0}
    real_listdir = os.listdir
    queued_dir = os.path.join(str(tmp_path / "q"), "queued")

    def counting(path="."):
        if os.path.abspath(str(path)) == os.path.abspath(queued_dir):
            calls["queued"] += 1
        return real_listdir(path)

    monkeypatch.setattr(os, "listdir", counting)
    q.invalidate()  # start cold (submit already warmed the cache)
    q.buckets()  # cold: one listdir warms the cache
    assert calls["queued"] == 1
    # one boundary's consults: order, counts, fairness probe, claim
    calls["queued"] = 0
    q.bucket_order()
    q.buckets()
    q.other_bucket_waiting(_KEY)
    got = q.claim(_KEY)
    assert got is not None
    assert calls["queued"] == 0, "boundary consults must ride the cache"
    # mutations keep the cache coherent without re-listing
    q.requeue(got)
    assert {r.id for _, r in q.snapshot_queued()} == {
        r.id for _, r in q.snapshot_queued()
    }
    assert calls["queued"] == 0
    # invalidate() (fleet: external writers) forces exactly one re-list
    q.invalidate()
    q.bucket_order()
    assert calls["queued"] == 1


def test_queue_claim_race_against_external_writer(tmp_path):
    """Fleet shape: a peer replica claims a queued file between our scan
    and our rename — the claim must skip it gracefully, never raise, and
    the stale cache entry is evicted."""
    q = DurableQueue(str(tmp_path / "q"), max_queue=8)
    a = q.submit(SimRequest(**_REQ, seed=0))
    b = q.submit(SimRequest(**_REQ, seed=1))
    q.snapshot_queued()  # warm the cache
    # the "peer": a second handle over the same dir steals request a
    peer = DurableQueue(str(tmp_path / "q"), max_queue=8)
    stolen = peer.claim()
    assert stolen.id == a.id
    # our stale-cached claim transparently lands on b
    got = q.claim()
    assert got is not None and got.id == b.id
    assert q.claim() is None


def test_queue_tenant_counts(tmp_path):
    q = DurableQueue(str(tmp_path / "q"), max_queue=8)
    q.submit(SimRequest(**_REQ, seed=0, tenant="a"))
    q.submit(SimRequest(**_REQ, seed=1, tenant="a"))
    q.submit(SimRequest(**_REQ, seed=2, tenant="b"))
    assert q.tenant_counts() == {"a": 2, "b": 1}
    q.claim()  # running still charges the tenant
    assert q.tenant_counts() == {"a": 2, "b": 1}
    done = q.claim()
    q.complete(done, {"nu": 1.0})  # resolved stops charging
    assert sum(q.tenant_counts().values()) == 2


def test_queue_qos_claim_order(tmp_path):
    q = DurableQueue(str(tmp_path / "q"), max_queue=8)
    be = q.submit(SimRequest(**_REQ, seed=0, priority="best-effort"))
    ia = q.submit(SimRequest(**_REQ, seed=1, priority="interactive"))
    ba = q.submit(SimRequest(**_REQ, seed=2))
    assert q.claim(_KEY).id == be.id  # plain claim is FIFO: class-blind
    q.requeue(be)
    # the QoS claim picks by class first, FIFO within a class
    assert q.claim(_KEY, qos=True).id == ia.id
    assert q.claim(_KEY, qos=True).id == ba.id
    assert q.claim(_KEY, qos=True).id == be.id


# -- durable continuations ----------------------------------------------------


def test_continuation_roundtrip_and_commit_marker(tmp_path):
    m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    m.init_random(0.1, seed=5)
    m.update_n(3)
    cdir = checkpoint.continuation_dir(str(tmp_path), "req123")
    checkpoint.write_continuation(
        cdir, m.state, base=3, time_base=0.03, meta={"id": "req123"}
    )
    assert checkpoint.continuation_exists(cdir)
    assert checkpoint.continuation_meta(cdir) == (3, 0.03)
    state, base, tbase = checkpoint.read_continuation(cdir, m.state)
    assert base == 3 and tbase == 0.03
    import numpy as np

    for name in m.state._fields:
        assert np.array_equal(
            np.asarray(getattr(state, name)),
            np.asarray(getattr(m.state, name)),
        )
    # the manifest is the commit marker: shards without it read as absent
    os.remove(os.path.join(cdir, checkpoint.CONTINUATION_MANIFEST))
    assert not checkpoint.continuation_exists(cdir)
    assert checkpoint.continuation_meta(cdir) is None
    with pytest.raises(checkpoint.CheckpointError, match="no committed"):
        checkpoint.read_continuation(cdir, m.state)


def test_continuation_digest_verification_rejects_corruption(tmp_path):
    m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    m.init_random(0.1, seed=5)
    cdir = checkpoint.continuation_dir(str(tmp_path), "reqX")
    checkpoint.write_continuation(cdir, m.state, base=1, time_base=0.01)
    shard = os.path.join(cdir, "shard_00000.h5")
    with open(shard, "r+b") as fh:  # flip bytes mid-file
        fh.seek(os.path.getsize(shard) // 2)
        fh.write(b"\xde\xad\xbe\xef")
    with pytest.raises(checkpoint.CheckpointError):
        checkpoint.read_continuation(cdir, m.state)
    # retire is idempotent and total
    checkpoint.remove_continuation(cdir)
    assert not os.path.exists(cdir)
    checkpoint.remove_continuation(cdir)


# -- the proxy tier -----------------------------------------------------------


def test_proxy_submit_status_stats_and_429(tmp_path):
    run_dir = str(tmp_path / "fleet")
    fleet = FleetConfig(replica_id="p1", default_quota=2)
    proxy = FleetProxy(run_dir, max_queue=3, fleet=fleet)
    proxy.start()
    try:
        host, port = proxy.address
        base = f"http://{host}:{port}"

        def post(payload):
            req = urllib.request.Request(
                base + "/requests",
                data=json.dumps(payload).encode(),
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status, json.loads(resp.read()), dict(resp.headers)
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read()), dict(err.headers)

        def get(path):
            try:
                with urllib.request.urlopen(base + path, timeout=30) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read())

        code, ack, _ = post(dict(_REQ, seed=0, tenant="t1"))
        assert code == 202 and ack["id"] and ack["trace_id"]
        # durable: the ack'd request is on disk, claimable by any replica
        q = DurableQueue(os.path.join(run_dir, "queue"), max_queue=3)
        assert q.counts()["queued"] == 1
        code, status = get(f"/requests/{ack['id']}")
        assert code == 200 and status["state"] == "queued"
        assert get("/requests/nope")[0] == 404
        # malformed: typed 400, nothing admitted
        assert post(dict(_REQ, dt=-1.0))[0] == 400
        assert post("not a dict")[0] == 400
        assert post(dict(_REQ, priority="nope"))[0] == 400
        # the QoS quota: tenant t1 holds 2/2 -> 429 with Retry-After + depth
        assert post(dict(_REQ, seed=1, tenant="t1"))[0] == 202
        code, body, headers = post(dict(_REQ, seed=2, tenant="t1"))
        assert code == 429 and body["reason"] == "quota"
        assert int(headers["Retry-After"]) >= 1
        assert body["queue_depth"] == 2 and body["retry_after_s"] >= 1
        # other tenants are unaffected until the queue itself fills
        assert post(dict(_REQ, seed=3, tenant="t2"))[0] == 202
        code, body, headers = post(dict(_REQ, seed=4, tenant="t2"))
        assert code == 429 and body["reason"] == "queue_full"
        assert "Retry-After" in headers
        # stats aggregate durable state: queue + tenants + leases + replicas
        code, stats = get("/stats")
        assert code == 200
        assert stats["queue"]["queued"] == 3
        assert stats["tenants"] == {"t1": 2, "t2": 1}
        assert stats["leases"] == {} and stats["replicas"] == []
        code, health = get("/healthz")
        assert code == 200 and health["ok"] is True
        assert health["replicas_alive"] == 0
        # /metrics renders this proxy's registry
        with urllib.request.urlopen(base + "/metrics", timeout=30) as resp:
            assert resp.status == 200
            text = resp.read().decode()
        assert "fleet_proxy_requests_total" in text
        # quota_rejected journaled in the proxy's own journal
        events = _replica_events(run_dir, "proxy-p1")
        names = [e["event"] for e in events]
        assert "quota_rejected" in names and "request_admitted" in names
    finally:
        proxy.stop()


def test_proxy_sees_replica_heartbeats(tmp_path):
    from rustpde_mpi_tpu.serve.fleet.proxy import (
        read_replica_status,
        write_replica_heartbeat,
    )

    run_dir = str(tmp_path / "fleet")
    write_replica_heartbeat(run_dir, "rA", {"draining": False})
    write_replica_heartbeat(run_dir, "rB", {"draining": True})
    status = read_replica_status(run_dir, ttl_s=60.0)
    assert [r["replica"] for r in status] == ["rA", "rB"]
    assert all(not r["stale"] for r in status)
    # a heartbeat older than the ttl reads stale
    old = os.path.join(run_dir, "replicas", "rA.json")
    past = time.time() - 120.0
    os.utime(old, (past, past))
    status = read_replica_status(run_dir, ttl_s=60.0)
    assert [r["stale"] for r in status] == [True, False]


def test_http_front_429_carries_retry_after_and_depth(tmp_path):
    """Satellite: the root front's 429 now carries a Retry-After header
    and a JSON body with the live queue depth + the rejection reason."""
    srv = SimServer(_cfg(tmp_path, max_queue=1))
    from rustpde_mpi_tpu.serve.http_front import HttpFront

    front = HttpFront(srv)
    front.start()
    try:
        host, port = front.address
        base = f"http://{host}:{port}"

        def post(payload):
            req = urllib.request.Request(
                base + "/requests",
                data=json.dumps(payload).encode(),
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status, json.loads(resp.read()), dict(resp.headers)
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read()), dict(err.headers)

        assert post(dict(_REQ, seed=0))[0] == 202
        code, body, headers = post(dict(_REQ, seed=1))
        assert code == 429
        assert body["reason"] == "queue_full"
        assert body["queue_depth"] == 1
        assert body["retry_after_s"] >= 1
        assert int(headers["Retry-After"]) == body["retry_after_s"]
    finally:
        front.stop()


# -- fleet-mode serving (single replica, in process) --------------------------


def test_fleet_replica_serves_with_leases(tmp_path):
    """One fleet-mode replica end-to-end: buckets claimed through leases
    (journaled lease_claimed/lease_released in the replica's own journal
    under replicas/<id>/), every request resolves, results match solo."""
    fleet = FleetConfig(replica_id="rA", lease_ttl_s=30.0)
    srv = SimServer(_cfg(tmp_path, fleet=fleet))
    ids = [srv.submit(dict(_REQ, seed=s)).id for s in range(3)]
    summary = srv.serve()
    assert summary["completed"] == 3 and summary["failed"] == 0
    assert summary["fleet"]["replica"] == "rA"
    events = _replica_events(str(tmp_path / "fleet"), "rA")
    names = [e["event"] for e in events]
    assert "lease_claimed" in names and "lease_released" in names
    # no lease files left behind after the clean release
    leases = os.listdir(os.path.join(str(tmp_path / "fleet"), "queue", "leases"))
    assert [n for n in leases if n.endswith(".json")] == []
    for rid in ids:
        res = srv.result(rid)
        m = Navier2D(17, 17, 1e4, 1.0, res["dt"], 1.0, "rbc", periodic=False)
        m.init_random(res["amp"], seed=res["seed"])
        m.update_n(res["steps"])
        assert res["nu"] == pytest.approx(float(m.eval_nu()), rel=1e-9)


def test_fleet_preemption_is_loss_free(tmp_path):
    """The QoS acceptance path in process: a best-effort request is
    mid-campaign when an interactive one with a deadline arrives — the
    lane is preempted (requeue-with-state, durably parked), the
    interactive request runs and finishes FIRST, and the preempted
    request still completes solo-equivalent."""
    fleet = FleetConfig(
        replica_id="rA", lease_ttl_s=60.0, preempt_slack_s=3600.0
    )
    srv = SimServer(_cfg(tmp_path, slots=1, fleet=fleet))
    be = srv.submit(dict(_REQ, seed=0, horizon=1.0, priority="best-effort"))
    box = {}

    def later():
        while srv.stats()["member_steps"] < 8:
            time.sleep(0.05)
        box["ia"] = srv.submit(
            dict(_REQ, seed=1, horizon=0.05, priority="interactive",
                 deadline_s=30.0)
        )

    t = threading.Thread(target=later)
    t.start()
    summary = srv.serve()
    t.join()
    ia = box["ia"]
    assert summary["completed"] == 2 and summary["failed"] == 0
    assert summary["fleet"]["preempted"] >= 1
    events = _replica_events(str(tmp_path / "fleet"), "rA")
    names = [e["event"] for e in events]
    assert "request_preempted" in names
    assert "continuation_persisted" in names
    pre = [e for e in events if e["event"] == "request_preempted"]
    assert pre[0]["id"] == be.id and pre[0]["preempted_for"] == ia.id
    assert pre[0]["steps_done"] > 0
    # the interactive request met its deadline and finished FIRST
    done = [e for e in events if e["event"] == "request_done"]
    assert done[0]["id"] == ia.id
    ia_res = srv.result(ia.id)
    assert ia_res["admission_to_first_observable_s"] < 30.0
    # the preempted request resumed mid-flight and stayed solo-equivalent
    sched = [
        e for e in events
        if e["event"] == "request_scheduled" and e.get("parked")
    ]
    assert sched and sched[0]["base"] > 0
    res = srv.result(be.id)
    m = Navier2D(17, 17, 1e4, 1.0, res["dt"], 1.0, "rbc", periodic=False)
    m.init_random(res["amp"], seed=0)
    m.update_n(res["steps"])
    assert res["nu"] == pytest.approx(float(m.eval_nu()), rel=1e-9)


def test_fleet_cross_bucket_preemption_drains_campaign(tmp_path):
    """Cross-bucket preemption must CLOSE the running campaign's claims:
    the parked best-effort victim lands back in the same bucket's queue,
    and an open refill would re-claim it at the same boundary forever.
    With the claims closed the campaign drains, the QoS-ordered pick
    takes the interactive bucket, and the victim still completes."""
    fleet = FleetConfig(
        replica_id="rA", lease_ttl_s=60.0, preempt_slack_s=3600.0
    )
    srv = SimServer(_cfg(tmp_path, slots=1, fleet=fleet))
    be = srv.submit(dict(_REQ, seed=0, horizon=1.0, priority="best-effort"))
    box = {}

    def later():
        while srv.stats()["member_steps"] < 8:
            time.sleep(0.05)
        # DIFFERENT bucket (dt differs): the cross-bucket emergency
        box["ia"] = srv.submit(
            dict(_REQ, dt=0.005, seed=1, horizon=0.05,
                 priority="interactive", deadline_s=60.0)
        )

    t = threading.Thread(target=later)
    t.start()
    summary = srv.serve()
    t.join()
    ia = box["ia"]
    assert summary["completed"] == 2 and summary["failed"] == 0
    assert summary["fleet"]["preempted"] >= 1
    events = _replica_events(str(tmp_path / "fleet"), "rA")
    pre = [e for e in events if e["event"] == "request_preempted"]
    assert pre and pre[0]["id"] == be.id and pre[0]["preempted_for"] == ia.id
    # the interactive (other-bucket) request finished before the victim
    done = [e for e in events if e["event"] == "request_done"]
    assert done[0]["id"] == ia.id
    # ... and the victim was NOT re-claimed in the preempting campaign:
    # exactly one preemption, no park/requeue churn
    assert len(pre) == 1
    res = srv.result(be.id)
    m = Navier2D(17, 17, 1e4, 1.0, res["dt"], 1.0, "rbc", periodic=False)
    m.init_random(res["amp"], seed=0)
    m.update_n(res["steps"])
    assert res["nu"] == pytest.approx(float(m.eval_nu()), rel=1e-9)


def test_fleet_resumes_peer_continuation_mid_flight(tmp_path):
    """Cross-replica continuation: a (dead) peer's durable park is
    re-claimed by a fresh replica, which resumes MID-FLIGHT (journaled
    continuation_resumed, steps > 0) and lands bit-close to the solo
    trajectory — the zero-lost acceptance shape without subprocesses."""
    run_dir = str(tmp_path / "fleet")
    m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    m.init_random(0.1, seed=3)
    m.update_n(5)
    req = SimRequest(**_REQ, seed=3, amp=0.1)
    cdir = checkpoint.continuation_dir(run_dir, req.id)
    checkpoint.write_continuation(cdir, m.state, base=5, time_base=0.05)
    fleet = FleetConfig(replica_id="rB", lease_ttl_s=30.0)
    srv = SimServer(_cfg(tmp_path, fleet=fleet))
    import dataclasses

    srv.queue.submit(dataclasses.replace(req, progress=5))
    summary = srv.serve()
    assert summary["completed"] == 1 and summary["failed"] == 0
    events = _replica_events(run_dir, "rB")
    resumed = [e for e in events if e["event"] == "continuation_resumed"]
    assert resumed and resumed[0]["steps"] == 5
    res = srv.result(req.id)
    assert res["steps"] == 10
    solo = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    solo.init_random(0.1, seed=3)
    solo.update_n(10)
    assert res["nu"] == pytest.approx(float(solo.eval_nu()), rel=1e-9)
    # consumed: the continuation dir was retired at completion
    assert not checkpoint.continuation_exists(cdir)


def test_fleet_breaks_dead_replica_lease_and_reclaims(tmp_path):
    """Failure detection in process: a 'dead' replica left a lease + a
    claimed (running/) request behind.  A live replica's sweep breaks the
    stale lease, re-enqueues exactly that bucket's requests, and serves
    them — journaled lease_broken + requests_reclaimed."""
    run_dir = str(tmp_path / "fleet")
    lease_root = os.path.join(run_dir, "queue", "leases")
    dead = LeaseManager(lease_root, "dead-replica", ttl_s=0.2)
    queue = DurableQueue(os.path.join(run_dir, "queue"), max_queue=8)
    req = queue.submit(SimRequest(**_REQ, seed=0))
    assert queue.claim().id == req.id  # the dead replica had claimed it
    dead.claim(_KEY)
    time.sleep(0.5)  # stale past the TTL
    fleet = FleetConfig(replica_id="live", lease_ttl_s=0.2)
    srv = SimServer(_cfg(tmp_path, fleet=fleet))
    summary = srv.serve()
    assert summary["completed"] == 1 and summary["failed"] == 0
    assert summary["fleet"]["leases_broken"] == 1
    events = _replica_events(run_dir, "live")
    names = [e["event"] for e in events]
    assert "lease_broken" in names
    reclaimed = [e for e in events if e["event"] == "requests_reclaimed"]
    assert reclaimed and reclaimed[0]["ids"] == [req.id]


def test_fleet_off_adds_zero_journal_rows(tmp_path):
    """The acceptance invariant: with fleet=None the lease/continuation/
    QoS machinery contributes NOTHING — no fleet journal rows, no
    replicas/ or leases/ or parked/ dirs, the single-replica layout
    byte-identical to PR 10's."""
    srv = SimServer(_cfg(tmp_path))
    srv.submit(dict(_REQ, seed=0))
    summary = srv.serve()
    assert summary["completed"] == 1
    assert "fleet" not in summary
    run_dir = str(tmp_path / "fleet")
    events = read_journal(os.path.join(run_dir, "journal.jsonl"))
    fleet_rows = [
        e for e in events
        if e["event"].startswith(("lease_", "continuation_", "quota_"))
        or e["event"] in ("request_preempted", "requests_reclaimed",
                          "campaign_fenced")
    ]
    assert fleet_rows == []
    assert not os.path.exists(os.path.join(run_dir, "replicas"))
    assert not os.path.exists(os.path.join(run_dir, "parked"))
    assert not os.path.exists(os.path.join(run_dir, "queue", "leases"))


# -- the multi-replica chaos soak (slow tier) ---------------------------------


def _spawn_fleet_proc(run_dir, args, log_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", RUSTPDE_X64="1")
    env.pop("RUSTPDE_FAULT", None)
    log = open(log_path, "w")
    return subprocess.Popen(
        [
            sys.executable,
            os.path.join(_REPO, "examples", "navier_rbc_fleet.py"),
            "--run-dir", run_dir, *args,
        ],
        stdout=log, stderr=subprocess.STDOUT, text=True, env=env, cwd=_REPO,
    ), log


@pytest.mark.slow
def test_fleet_chaos_soak_replica_sigkill(tmp_path):
    """The acceptance gate: 1 proxy + 2 replicas over one shared queue,
    mixed-priority traffic submitted through the proxy, one replica
    SIGKILLed mid-campaign while it holds leases and durable parked
    continuations -> ZERO requests lost, the survivor breaks the dead
    replica's lease (journaled lease_broken) and resumes its requests
    MID-TRAJECTORY from the durable parked state (continuation_resumed,
    steps > 0), and a resumed request's result matches the solo rerun to
    rtol 1e-9."""
    run_dir = str(tmp_path / "fleet")
    os.makedirs(run_dir, exist_ok=True)
    procs, logs = [], []

    def spawn(args, name):
        p, log = _spawn_fleet_proc(
            run_dir, args, os.path.join(run_dir, f"{name}.log")
        )
        procs.append(p)
        logs.append(log)
        return p

    try:
        proxy = spawn(["--proxy", "--lease-ttl-s", "3"], "proxy")
        addr = None
        deadline = time.time() + 120
        while time.time() < deadline and addr is None:
            time.sleep(0.2)
            try:
                with open(os.path.join(run_dir, "proxy.log")) as fh:
                    for line in fh:
                        if line.startswith("{"):
                            addr = json.loads(line)["address"]
                            break
            except OSError:
                pass
        assert addr, "proxy never bound"
        base = f"http://{addr[0]}:{addr[1]}"
        common = [
            "--replica", "--daemon", "--lease-ttl-s", "3",
            "--heartbeat-s", "0.2", "--slots", "2", "--chunk-steps", "8",
            "--ckpt-every-s", "1000",
        ]
        rA = spawn([*common, "--replica-id", "rA"], "rA")
        rB = spawn([*common, "--replica-id", "rB"], "rB")

        def post(payload):
            req = urllib.request.Request(
                base + "/requests",
                data=json.dumps(payload).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())

        n_req = 8
        ids = []
        for seed in range(n_req):
            pri = "best-effort" if seed % 2 else "batch"
            code, ack = post(
                dict(_REQ, seed=seed, horizon=2.0 + 0.08 * seed,
                     priority=pri, tenant=f"t{seed % 2}")
            )
            assert code == 202
            ids.append(ack["id"])

        # kill whichever replica persisted a mid-flight continuation first
        def persisted(rid):
            try:
                return any(
                    e["event"] == "continuation_persisted"
                    and e.get("steps", 0) > 0
                    for e in _replica_events(run_dir, rid)
                )
            except Exception:
                return False

        victim = None
        deadline = time.time() + 600
        while time.time() < deadline and victim is None:
            time.sleep(0.2)
            for rid in ("rA", "rB"):
                if persisted(rid):
                    victim = rid
                    break
        assert victim, "no mid-flight continuation ever persisted"
        vic, sur = (rA, "rB") if victim == "rA" else (rB, "rA")
        vic.send_signal(signal.SIGKILL)

        # the fleet drains everything: zero lost, zero failed
        queue = DurableQueue(os.path.join(run_dir, "queue"), max_queue=512)
        deadline = time.time() + 900
        while time.time() < deadline:
            counts = queue.counts()
            if counts["done"] == n_req and counts["queued"] == 0 \
                    and counts["running"] == 0:
                break
            time.sleep(0.5)
        assert counts == {
            "queued": 0, "running": 0, "done": n_req, "failed": 0
        }, counts

        # graceful teardown of the survivors
        sur_proc = rB if victim == "rA" else rA
        sur_proc.send_signal(signal.SIGTERM)
        sur_proc.wait(timeout=300)
        proxy.send_signal(signal.SIGTERM)
        proxy.wait(timeout=60)

        events = _replica_events(run_dir, sur)
        names = [e["event"] for e in events]
        assert "lease_broken" in names
        assert "requests_reclaimed" in names
        resumed = [
            e for e in events
            if e["event"] == "continuation_resumed" and e.get("steps", 0) > 0
        ]
        assert resumed, "survivor never resumed mid-flight from durable state"
        # lease-break-to-reclaim is prompt (well under one TTL)
        breaks = [e for e in events if e["event"] == "lease_broken"]
        claims = [
            e for e in events
            if e["event"] == "lease_claimed" and e["t"] > breaks[0]["t"]
        ]
        assert claims and claims[0]["t"] - breaks[0]["t"] < 3.0

        # solo-equivalence of a mid-flight-resumed request
        rid = resumed[0]["id"]
        with open(os.path.join(run_dir, "queue", "done", f"{rid}.json")) as fh:
            res = json.load(fh)["result"]
        m = Navier2D(17, 17, 1e4, 1.0, res["dt"], 1.0, "rbc", periodic=False)
        m.init_random(res["amp"], seed=res["seed"])
        m.update_n(res["steps"])
        assert res["nu"] == pytest.approx(float(m.eval_nu()), rel=1e-9)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
