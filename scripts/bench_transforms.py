"""A/B transform microbench: dense folded GEMM vs four-step plans (VERDICT
r2 #1 'done' criterion).  Slope-timed (relay fixed cost cancels).

Usage: RUSTPDE_X64=0 python scripts/bench_transforms.py [--iters 128]
       [--sizes 1024,2048] [--batch 1025] [--n1 0 (auto) | k]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, state, iters):
    import functools

    import jax
    import numpy as np

    def body(c, _):
        return fn(c), None

    @functools.partial(jax.jit, static_argnames=("length",))
    def run(s, length):
        return jax.lax.scan(body, s, None, length=length)[0]

    def once(length):
        out = run(state, length)
        leaf = jax.tree.leaves(out)[0]
        np.asarray(leaf[(0,) * leaf.ndim])  # 1-element readback: slicing on
        # device first -- np.asarray(whole) would stream MBs through the
        # relay and its transfer-time variance swamps the timing

    times = {}
    for length in (iters, 4 * iters):
        once(length)  # compile + warm
        best = float("inf")
        for _ in range(3):  # min-of-3: the relay adds 10-30% run noise
            t0 = time.perf_counter()
            once(length)
            best = min(best, time.perf_counter() - t0)
        times[length] = best
    return (times[4 * iters] - times[iters]) / (3 * iters) * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=128)
    ap.add_argument("--sizes", default="1024,2048")
    ap.add_argument("--batch", type=int, default=1025)
    ap.add_argument("--n1", type=int, default=0)
    args = ap.parse_args()
    os.environ.setdefault("RUSTPDE_X64", "0")

    import jax.numpy as jnp
    import numpy as np

    from rustpde_mpi_tpu import config
    from rustpde_mpi_tpu.ops import chebyshev as chb
    from rustpde_mpi_tpu.ops import fourier as fou
    from rustpde_mpi_tpu.ops import fourstep
    from rustpde_mpi_tpu.ops.folded import FoldedMatrix

    rdt = config.real_dtype()
    to_dev = lambda m: jnp.asarray(np.asarray(m, dtype=rdt))  # noqa: E731
    rng = np.random.default_rng(0)
    B = args.batch
    n1 = args.n1 or None
    it = args.iters
    print(f"platform={config.default_device_kind()} dtype={np.dtype(rdt).name} batch={B}")

    for n in (int(s) for s in args.sizes.split(",")):
        v = to_dev(rng.standard_normal((n, B)))
        # --- DCT core of size n+1 (what a Chebyshev axis transform runs) ---
        np1 = n + 1
        vp = to_dev(rng.standard_normal((np1, B)))
        dense_f = FoldedMatrix(chb.analysis_matrix(np1), to_dev)
        t_dense = timeit(lambda a: dense_f.apply(a, 0), vp, it)
        plan = fourstep.Dct1Plan(np1, to_dev, n1=n1)
        t_fast = timeit(plan.apply, vp, it)
        f1, f2 = plan._plan.n1, plan._plan.n2
        print(
            f"DCT n={np1:5d}: dense {t_dense:7.3f} ms  fourstep({f1}x{f2})"
            f" {t_fast:7.3f} ms  ratio {t_dense / max(t_fast, 1e-9):5.2f}x"
        )
        # --- split r2c of size n ---
        dense_s = FoldedMatrix(fou.split_forward_matrix(n), to_dev)
        m = n // 2 + 1

        # slice to the input shape so the timing scan carry is well-typed
        t_dense = timeit(lambda a: dense_s.apply(a, 0)[:n], v, it)
        rplan = fourstep.RfftPlan(n, to_dev, n1=n1)
        t_fast = timeit(lambda a: rplan.split(a)[:n], v, it)
        print(
            f"r2c n={n:5d}: dense {t_dense:7.3f} ms  fourstep({rplan.n1}x{rplan.n2})"
            f" {t_fast:7.3f} ms  ratio {t_dense / max(t_fast, 1e-9):5.2f}x"
        )
        # --- irfft of size n ---
        s2m = to_dev(rng.standard_normal((2 * m, B)))
        dense_b = FoldedMatrix(fou.split_backward_matrix(n), to_dev)
        # pad the (n, B) synthesis back to the (2m, B) carry shape
        t_dense = timeit(
            lambda a: jnp.concatenate(
                [dense_b.apply(a, 0), jnp.zeros_like(a[: 2 * m - n])], 0
            ),
            s2m,
            it,
        )
        iplan = fourstep.IrfftPlan(n, to_dev, n1=n1)
        t_fast = timeit(
            lambda a: jnp.concatenate([iplan.apply(a), jnp.zeros_like(a[: 2 * m - n])], 0),
            s2m,
            it,
        )
        print(
            f"c2r n={n:5d}: dense {t_dense:7.3f} ms  fourstep({iplan.n1}x{iplan.n2})"
            f" {t_fast:7.3f} ms  ratio {t_dense / max(t_fast, 1e-9):5.2f}x"
        )
        # --- c2c of size n (both split planes) ---
        w = to_dev(rng.standard_normal((2, n, B)))
        ccos = FoldedMatrix(fou.dft_cos_matrix(n), to_dev)
        csin = FoldedMatrix(fou.dft_sin_matrix(n), to_dev)

        def dense_c2c(a):
            re = ccos.apply(a[0], 0) + csin.apply(a[1], 0)
            im = ccos.apply(a[1], 0) - csin.apply(a[0], 0)
            return jnp.stack([re, im])

        t_dense = timeit(dense_c2c, w, it)
        cplan = fourstep.C2cPlan(n, to_dev, sign=-1.0, n1=n1)

        def fast_c2c(a):
            re, im = cplan.apply(a[0], a[1])
            return jnp.stack([re, im])

        t_fast = timeit(fast_c2c, w, it)
        print(
            f"c2c n={n:5d}: dense {t_dense:7.3f} ms  fourstep({cplan.n1}x{cplan.n2})"
            f" {t_fast:7.3f} ms  ratio {t_dense / max(t_fast, 1e-9):5.2f}x"
        )


if __name__ == "__main__":
    main()
