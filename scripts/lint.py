"""Project lint CLI: the repo's own AST rules + the curated generic layer.

Usage:
    python scripts/lint.py                    # whole repo, exit 1 on NEW findings
    python scripts/lint.py rustpde_mpi_tpu    # subtree only
    python scripts/lint.py --json             # machine-readable payload
    python scripts/lint.py --update-baseline  # grandfather current findings
                                              # (then EDIT the reasons)
    python scripts/lint.py --show-baselined   # list grandfathered findings

Exit codes: 0 clean (baselined findings allowed), 1 new findings, 2 stale
baseline entries (the flagged code changed or was fixed — prune the entry).
Rule inventory and the historical bug each rule encodes: README "Static
analysis & sanitizer".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tools.lint import core  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="repo-relative files/dirs (default: full scope)")
    ap.add_argument("--json", action="store_true", help="JSON payload to stdout")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write current NEW findings into the baseline (edit reasons after)")
    ap.add_argument("--show-baselined", action="store_true")
    ap.add_argument("--baseline", default=core.DEFAULT_BASELINE)
    args = ap.parse_args()

    result = core.run_lint(root=_REPO, paths=args.paths or None,
                           baseline_path=args.baseline)

    if args.update_baseline:
        entries = core.load_baseline(args.baseline)
        for f in result.new:
            entries.append(
                {
                    "rule": f.rule,
                    "path": f.path,
                    "context": f.context,
                    "snippet": f.snippet,
                    "reason": "TODO: write why this finding is acceptable",
                }
            )
        core.save_baseline(entries, args.baseline)
        print(f"baselined {len(result.new)} findings into {args.baseline} "
              "— now edit the reasons")
        return 0

    if args.json:
        print(json.dumps(
            {
                "engine": result.engine,
                "files": result.files,
                "new": [f.to_dict() for f in result.new],
                "counts": result.counts,
                "baselined_counts": result.baselined_counts,
                "suppressed": result.suppressed,
                "stale_baseline": result.stale_baseline,
            },
            indent=1,
        ))
    else:
        for f in result.new:
            print(f)
        if args.show_baselined:
            for f in result.baselined:
                print(f"[baselined] {f}")
        print(
            f"lint: {result.files} files, engine={result.engine}, "
            f"{len(result.new)} new, {len(result.baselined)} baselined, "
            f"{result.suppressed} suppressed, {len(result.stale_baseline)} stale"
        )
    # full-scope runs enforce baseline hygiene; partial runs can't tell
    # whether an entry is stale (its file may simply be out of scope)
    if result.new:
        return 1
    if result.stale_baseline and not args.paths:
        for e in result.stale_baseline:
            print(f"stale baseline entry: {e['rule']} {e['path']} — {e.get('snippet','')!r}")
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
