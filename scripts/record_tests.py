"""Run the full test suite (fast + slow tiers) and record the result in
``TESTS.json`` at the repo root (VERDICT r4 weak #6: the slow tier —
multiprocess, examples, production-shape mesh checks — must leave a recorded
cadence, not just an on-demand env knob).

Usage:  python scripts/record_tests.py            # full suite (RUSTPDE_SLOW=1)
        python scripts/record_tests.py --fast     # fast tier only

Per-test durations (``--durations``-style) are parsed from every run and
recorded in TESTS.json, and the FAST tier enforces a per-test wall budget
(``RUSTPDE_TEST_BUDGET_S``, default 45 s per test call — the slowest
tier-1 test sits at ~20 s, so the gate only trips on a genuine 2x+
regression, not scheduler noise on a contended box): a tier-1 test
that outgrows its budget fails the run (rc=3) the PR it regresses, instead
of silently eating the suite's 870 s clock until the whole tier times out
(the rc=124-at-HEAD failure mode this repo has already hit once).
"""

import argparse
import datetime
import json
import os
import re
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true", help="skip the slow tier")
    args = ap.parse_args()

    env = dict(os.environ)
    if not args.fast:
        env["RUSTPDE_SLOW"] = "1"
    tier = "fast" if args.fast else "full (RUSTPDE_SLOW=1)"
    tier_key = "fast" if args.fast else "full"
    budget_s = float(os.environ.get("RUSTPDE_TEST_BUDGET_S", "45"))
    timeout_s = 7200
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "tests/", "-q", "--durations=0"],
            cwd=_REPO,
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as exc:
        # a hung suite must still leave a TESTS.json entry: record the
        # timeout (rc=124, the coreutils convention) before exiting nonzero
        out = exc.stdout
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        record = {
            "tier": tier,
            "summary": f"timeout: suite exceeded {timeout_s}s",
            "passed": 0,
            "failed": 0,
            "skipped": 0,
            "dots_passed": _dots_passed(out or ""),  # how far the run got
            "wall_s": round(time.time() - t0, 1),
            "returncode": 124,
            "date": _utc_now(),
        }
        _persist(record, tier_key)
        print(json.dumps(record))
        sys.stderr.write((out or "")[-4000:])
        return 124
    wall = time.time() - t0
    tail = (proc.stdout or "").strip().splitlines()[-1:] or [""]
    summary = tail[0]
    # normalize "errors" -> "error" so the plural pytest summary counts too
    counts = {kind.rstrip("s") if kind.startswith("error") else kind: int(num)
              for num, kind in
              re.findall(r"(\d+) (passed|failed|skipped|errors?)", summary)}
    record = {
        "tier": tier,
        "summary": summary,
        "passed": counts.get("passed", 0),
        "failed": counts.get("failed", 0) + counts.get("error", 0),
        "skipped": counts.get("skipped", 0),
        # the tier-1 driver's own progress metric (ROADMAP "Tier-1 verify"
        # counts '.' chars on the -q progress lines as DOTS_PASSED): record
        # it per run so an IO/test-duration regression that changes how far
        # the suite gets is visible across PRs even when the summary line
        # is missing (hang/kill)
        "dots_passed": _dots_passed(proc.stdout or ""),
        # per-test duration profile (the 15 slowest call phases) + budget
        # verdict: tier-1 regressions are caught per-PR, not when the whole
        # suite first blows its 870 s clock
        "durations": dict(_durations(proc.stdout or "")[:15]),
        "budget_s": budget_s,
        "over_budget": _over_budget(proc.stdout or "", budget_s),
        "wall_s": round(wall, 1),
        "returncode": proc.returncode,
        # sharded-checkpoint IO counters from the last recorded
        # shardedio129 bench row (shard count, bytes/host, gate flags) —
        # the durability harness's footprint rides the test record so a
        # shard-layout regression is visible across PRs
        "sharded_io": _sharded_io_counters(),
        # multihost-serve counters from the serve129 row's 2-proc CPU leg
        # (drain/replan/dt-adjust trajectory of the root-coordinated
        # scheduler) — the multihost serving path gets the same tracked
        # record the two-phase writer has
        "serve_mp": _serve_mp_counters(),
        # HA-fleet counters from the serve129 fleet leg (replicas
        # spawned, leases broken, preemptions, zero-lost flag) — the
        # replicated front door gets the same tracked record
        "fleet": _fleet_counters(),
        # autoscaling-controller counters from the autoscale129 chaos
        # soak (decisions, spawn/retire counts, preemptions, admission
        # p99, loss gates) — the control loop gets the same tracked
        # record the fleet it drives has
        "autoscale": _autoscale_counters(),
        # gang-scheduled sub-mesh serving counters from the
        # serve_submesh129 chaos pair (gang formations, typed member
        # losses, reclaim/requeue trajectory, solo-parity and co-resident
        # latency gates) — two-level serving gets the same tracked record
        # the flat multihost scheduler has
        "gang_serve": _gang_serve_counters(),
        # cold-start elimination counters from the coldstart129 legs
        # (cache/warm-pool/canonicalization TTFC + restart walls and
        # their gates) — the serving stack's p99-compile story gets the
        # same tracked record its chaos legs have
        "coldstart": _coldstart_counters(),
        # SDC-defense counters from the integrity129 row (digest-stream
        # overhead, bit-equal trajectory, injected-bitflip caught/rolled-
        # back gates) — the integrity layer gets the same tracked record
        # its chaos siblings have
        "integrity": _integrity_counters(),
        # per-model solo-vs-ensemble parity deltas (workloads satellite):
        # recorded into PARITY.json too, so cross-model vmap/scan drift
        # shows up per-PR next to the Nu-parity numbers
        "workloads": _workloads_parity(),
        # fused-Pallas-vs-dense convection parity per layout (max rel diff,
        # interpreter mode) — merged into PARITY.json under "pallas_conv"
        # so kernel drift is tracked per-PR like the Nu trajectories
        "pallas_conv": _pallas_conv_parity(),
        # fused-step (Helmholtz/Poisson solve megakernel) vs dense solver
        # chain, 5-step trajectory parity per layout — merged into
        # PARITY.json under "pallas_step" next to the conv kernel trend
        "pallas_step": _pallas_step_parity(),
        # in-scan stats engine vs the eager legacy accumulator (max rel
        # diff per accumulated field) — merged into PARITY.json under
        # "stats" so accumulator drift is tracked per-PR too
        "stats": _stats_parity(),
        # telemetry inventory (METRICS.json written alongside): the metric
        # names an instrumented run registers — a per-PR record of the
        # observable vocabulary, like the journal schema rows
        "metrics": _metrics_snapshot(),
        # static-analysis payload (LINT.json written alongside): rule ->
        # count for both lint layers + baseline size, with a delta gate —
        # NEW findings (or stale baseline entries) fail the record run
        "lint": _lint_payload(),
        # perf-trend payload (TREND.json written alongside): per-config
        # BENCH_r*/BENCH_FULL trajectories with a noise-band regression
        # gate — an un-acked regression fails the record run via rc=5
        "trend": _trend_payload(),
        "date": _utc_now(),
    }
    _persist(record, tier_key)
    print(json.dumps(record))
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout[-4000:])
        return proc.returncode
    lint = record["lint"] or {}
    if lint.get("clean") is False:
        sys.stderr.write(
            "lint baseline-delta gate: new findings "
            f"{lint.get('counts')} (stale baseline: {lint.get('stale')}) — "
            "run scripts/lint.py\n"
        )
        return 4
    trend = record["trend"] or {}
    if trend.get("clean") is False:
        sys.stderr.write(
            "perf-trend gate: un-acked regressions "
            f"{trend.get('regressions_unacked')} — investigate, or ack with "
            "a written reason: scripts/bench_trend.py --ack <config> "
            "--reason '...'\n"
        )
        return 5
    # the budget gate applies to the FAST (= tier-1) selection only: slow-
    # tier tests (multiprocess spawns, soaks) legitimately run for minutes
    if args.fast and record["over_budget"]:
        sys.stderr.write(
            f"tier-1 per-test budget ({budget_s:.0f}s) exceeded by: "
            f"{record['over_budget']}\n"
        )
        return 3
    return 0


_DURATION_LINE = re.compile(
    r"^\s*([0-9]+\.[0-9]+)s\s+(call|setup|teardown)\s+(\S+)\s*$"
)


def _durations(out: str) -> list:
    """``[(testid, seconds), ...]`` slowest-first from pytest's
    ``--durations=0`` report (call phases only: setup/teardown time is
    fixture-shared and double-counts across tests)."""
    found = []
    for line in out.splitlines():
        m = _DURATION_LINE.match(line)
        if m and m.group(2) == "call":
            found.append((m.group(3), float(m.group(1))))
    found.sort(key=lambda kv: -kv[1])
    return found


def _over_budget(out: str, budget_s: float) -> list:
    """Test ids whose call phase exceeded the per-test budget."""
    return [tid for tid, s in _durations(out) if s > budget_s]


def _dots_passed(out: str) -> int:
    """Count pass-dots on pytest -q progress lines — the same
    ``^[.FEsx]+( *\\[ *[0-9]+%\\])?$`` line shape (and dot count) the
    ROADMAP tier-1 verify greps as DOTS_PASSED."""
    progress = re.compile(r"^[.FEsx]+( *\[ *[0-9]+%\])?$")
    return sum(
        line.count(".")
        for line in out.splitlines()
        if progress.match(line.strip())
    )


def _sharded_io_counters() -> dict | None:
    """Shard/bytes counters from BENCH_FULL.json's ``shardedio129`` row
    (None when the config was never benched on this platform)."""
    try:
        with open(os.path.join(_REPO, "BENCH_FULL.json")) as f:
            row = json.load(f)["results"]["shardedio129"]
        return {
            "shards": row.get("shards"),
            "bytes_host": row.get("bytes_host"),
            "bytes_total": row.get("bytes_total"),
            "manifest_verify_ok": row.get("manifest_verify_ok"),
            "cross_topology_restore_equal": row.get(
                "cross_topology_restore_equal"
            ),
        }
    except (OSError, ValueError, KeyError):
        return None


def _serve_mp_counters() -> dict | None:
    """Drain/replan/dt-adjust counters from BENCH_FULL.json's ``serve129``
    2-process leg (None when the config was never benched — or predates
    the multihost scheduler)."""
    try:
        with open(os.path.join(_REPO, "BENCH_FULL.json")) as f:
            row = json.load(f)["results"]["serve129"]
        mp = row.get("multiprocess")
        if not isinstance(mp, dict):
            return None
        return {
            key: mp.get(key)
            for key in (
                "nproc",
                "completed",
                "drains",
                "requeued",
                "replans",
                "dt_adjusts",
                "restored_mid_trajectory",
                "zero_lost",
                "error",
            )
            if key in mp
        }
    except (OSError, ValueError, KeyError):
        return None


def _fleet_counters() -> dict | None:
    """HA-fleet counters from BENCH_FULL.json's ``serve129`` fleet leg
    (proxy + 2 leased replicas, replica SIGKILL mid-campaign): replicas
    spawned, leases broken, preemptions, break->reclaim latency and the
    zero-lost / reclaimed-with-state flags.  None when the config was
    never benched — or predates the fleet layer."""
    try:
        with open(os.path.join(_REPO, "BENCH_FULL.json")) as f:
            row = json.load(f)["results"]["serve129"]
        fleet = row.get("fleet")
        if not isinstance(fleet, dict):
            return None
        return {
            key: fleet.get(key)
            for key in (
                "replicas",
                "proxies",
                "requests",
                "leases_broken",
                "preemptions",
                "resumed_mid_flight",
                "lease_break_to_reclaim_s",
                "zero_lost",
                "reclaimed_with_state",
                "error",
            )
            if key in fleet
        }
    except (OSError, ValueError, KeyError):
        return None


def _autoscale_counters() -> dict | None:
    """Controller counters from BENCH_FULL.json's ``autoscale129`` row
    (chaos soak: Poisson notice-SIGTERM/SIGKILL preemptions against an
    autoscaled fleet): decisions taken, replicas spawned/retired,
    preemption mix, admission p99 and the zero-lost /
    reclaimed-with-state / SLO gates.  None when the config was never
    benched — or predates the autoscaler."""
    try:
        with open(os.path.join(_REPO, "BENCH_FULL.json")) as f:
            row = json.load(f)["results"]["autoscale129"]
        return {
            key: row.get(key)
            for key in (
                "requests",
                "decisions",
                "spawned",
                "retired",
                "preempts_notice",
                "preempts_kill",
                "resumed_mid_flight",
                "admission_p50_s",
                "admission_p99_s",
                "zero_lost",
                "reclaimed_with_state",
                "slo_ok",
                "error",
            )
            if key in row
        }
    except (OSError, ValueError, KeyError):
        return None


def _coldstart_counters() -> dict | None:
    """Cold-start counters from BENCH_FULL.json's ``coldstart129`` row
    (persistent compile cache + warm campaign pool + admission
    canonicalization legs): never-seen-key TTFC and restart-to-first-
    result cold vs warm, the zero-jit warm admission / recompile-flat /
    canonicalization-parity gates.  None when the config was never
    benched — or predates the warm pool."""
    try:
        with open(os.path.join(_REPO, "BENCH_FULL.json")) as f:
            row = json.load(f)["results"]["coldstart129"]
        return {
            key: row.get(key)
            for key in (
                "ttfc_cold_s",
                "ttfc_warm_s",
                "restart_to_first_result_cold_s",
                "restart_to_first_result_prime_s",
                "restart_to_first_result_warm_s",
                "warm_pool_hits",
                "warm_leg_compile_builds",
                "recompiles",
                "canonicalized_parity_rel",
                "parity_rtol",
                "zero_jit_warm",
                "ttfc_improved",
                "restart_improved",
                "recompile_flat",
                "parity_ok",
                "error",
            )
            if key in row
        }
    except (OSError, ValueError, KeyError):
        return None


def _gang_serve_counters() -> dict | None:
    """Two-level serving counters from BENCH_FULL.json's
    ``serve_submesh129`` row (clean baseline + gang-kill chaos pair on
    the 2-process sub-mesh harness): gang formations, typed member
    losses, the reclaim trajectory and the zero-lost / solo-parity /
    co-resident-latency gates.  None when the config was never benched —
    or predates gang scheduling."""
    try:
        with open(os.path.join(_REPO, "BENCH_FULL.json")) as f:
            row = json.load(f)["results"]["serve_submesh129"]
        out = {
            key: row.get(key)
            for key in (
                "requests_gang",
                "requests_vmapped",
                "coresident_p99_factor",
                "solo_rel_err_max",
                "zero_lost",
                "gang_killed",
                "gang_reclaimed",
                "solo_ok",
                "coresident_ok",
                "error",
            )
            if key in row
        }
        chaos = row.get("chaos")
        if isinstance(chaos, dict):
            out["gang_formed"] = chaos.get("gang_formed")
            out["gang_member_lost"] = chaos.get("gang_member_lost")
            out["restored_mid_trajectory"] = chaos.get(
                "restored_mid_trajectory"
            )
        return out
    except (OSError, ValueError, KeyError):
        return None


def _integrity_counters() -> dict | None:
    """SDC-defense counters from BENCH_FULL.json's ``integrity129`` row
    (digests-on vs off matched windows + the injected-bitflip detection
    pair): overhead factor, bit-equal flags and the caught/rolled-back
    gate.  None when the config was never benched — or predates the
    integrity layer."""
    try:
        with open(os.path.join(_REPO, "BENCH_FULL.json")) as f:
            row = json.load(f)["results"]["integrity129"]
        return {
            key: row.get(key)
            for key in (
                "integrity_overhead_x",
                "integrity_overhead_ok",
                "integrity_bit_equal",
                "sdc_caught",
                "sdc_bit_equal",
                "error",
            )
            if key in row
        }
    except (OSError, ValueError, KeyError):
        return None


_WORKLOADS_CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu.workloads import solo_ensemble_parity
print("WORKLOADS_JSON " + json.dumps(solo_ensemble_parity(steps=6)))
"""


def _parity_probe(child_src: str, marker: str, key: str, value_key: str) -> dict:
    """Shared harness behind every PARITY.json probe: run ``child_src`` as
    a CPU child, parse the ``marker``-prefixed JSON line, and atomically
    merge the payload under ``key`` next to the Nu-parity trajectories.
    Best-effort: a failure records the error string instead of killing the
    test record."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child_src % {"repo": _REPO}],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=_REPO,
        )
        line = next(
            ln for ln in proc.stdout.splitlines() if ln.startswith(marker)
        )
        values = json.loads(line[len(marker):])
    except Exception as exc:  # noqa: BLE001 — recording must not fail the run
        return {"error": f"{type(exc).__name__}: {exc}"}
    payload = {value_key: values, "date": _utc_now()}
    parity_path = os.path.join(_REPO, "PARITY.json")
    try:
        with open(parity_path) as f:
            parity = json.load(f)
    except (OSError, ValueError):
        parity = {}
    parity[key] = payload
    tmp = f"{parity_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(parity, f, indent=1)
    os.replace(tmp, parity_path)
    return payload


def _workloads_parity() -> dict | None:
    """Per-model-kind solo-vs-ensemble parity deltas (max relative state
    deviation of a K=2 vmapped campaign vs member-wise solo runs, per
    registered model kind), merged into PARITY.json under ``"workloads"``."""
    return _parity_probe(_WORKLOADS_CHILD, "WORKLOADS_JSON ", "workloads", "deltas")


_PALLAS_CONV_CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RUSTPDE_X64", "1")
sys.path.insert(0, %(repo)r)
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from rustpde_mpi_tpu.bases import (
    Space2, cheb_dirichlet, chebyshev, fourier_r2c, fourier_r2c_split,
)
from rustpde_mpi_tpu.ops.pallas_conv import FusedConv

def delta(sp, fs, seed=0):
    fc = FusedConv(sp, fs, (1.0, 1.0))
    rng = np.random.default_rng(seed)
    nx, ny = sp.shape_physical
    ux = jnp.asarray(rng.standard_normal((nx, ny)))
    uy = jnp.asarray(rng.standard_normal((nx, ny)))
    vhat = sp.forward(jnp.asarray(rng.standard_normal((nx, ny))))
    ref = np.asarray(fc.reference(ux, uy, vhat))
    out = np.asarray(fc.apply(ux, uy, vhat))
    return float(np.abs(out - ref).max() / (np.abs(ref).max() or 1.0))

os.environ["RUSTPDE_SEP"] = "1"
deltas = {
    "confined_sep": delta(
        Space2(cheb_dirichlet(33), cheb_dirichlet(33), method="matmul", sep=True),
        Space2(chebyshev(33), chebyshev(33), method="matmul", sep=True),
    ),
    "periodic_complex": delta(
        Space2(fourier_r2c(16), cheb_dirichlet(17)),
        Space2(fourier_r2c(16), chebyshev(17)),
    ),
    "split_sep": delta(
        Space2(fourier_r2c_split(16), cheb_dirichlet(17), method="matmul"),
        Space2(fourier_r2c_split(16), chebyshev(17), method="matmul"),
    ),
}
print("PALLAS_CONV_JSON " + json.dumps(deltas))
"""


def _pallas_conv_parity() -> dict | None:
    """Max relative dense-vs-Pallas deviation of the fused convection chain
    per layout (CPU interpreter mode, f64), merged into PARITY.json under
    ``"pallas_conv"``."""
    return _parity_probe(
        _PALLAS_CONV_CHILD, "PALLAS_CONV_JSON ", "pallas_conv", "max_rel_diff"
    )


_PALLAS_STEP_CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RUSTPDE_X64", "1")
sys.path.insert(0, %(repo)r)
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import rustpde_mpi_tpu as rp

def build(periodic, nx, ny, kernel):
    os.environ["RUSTPDE_STEP_KERNEL"] = kernel
    m = rp.Navier2D(nx, ny, 1e4, 1.0, 5e-3, 1.0, "rbc", periodic=periodic)
    m.set_velocity(0.1, 1.0, 1.0)
    m.set_temperature(0.1, 1.0, 1.0)
    return m

def delta(periodic, nx, ny, env=()):
    for k, v in env:
        os.environ[k] = v
    try:
        d = build(periodic, nx, ny, "dense")
        p = build(periodic, nx, ny, "pallas")
        assert p._step_impl is not None
        d.update_n(5)
        p.update_n(5)
        # per-leaf deviations floored by the physical-field scale (the
        # pseudo-pressure is ~zero at near-incompressibility: its own max
        # is roundoff noise, not a meaningful denominator)
        scale0 = max(
            float(np.abs(np.asarray(x)).max())
            for x in (d.state.temp, d.state.velx, d.state.vely)
        )
        rel = 0.0
        for a, b in zip(p.state, d.state):
            a, b = np.asarray(a), np.asarray(b)
            den = max(float(np.abs(b).max()), scale0, 1e-30)
            rel = max(rel, float(np.abs(a - b).max() / den))
        return rel
    finally:
        for k, _ in env:
            os.environ.pop(k, None)
        os.environ.pop("RUSTPDE_STEP_KERNEL", None)

deltas = {
    "confined": delta(False, 17, 17),
    "periodic_complex": delta(True, 16, 17),
    "confined_sep": delta(False, 33, 33, (("RUSTPDE_FORCE_TPU_PATH", "1"),)),
    "split_sep": delta(
        True, 16, 17,
        (("RUSTPDE_FORCE_TPU_PATH", "1"), ("RUSTPDE_SEP", "1")),
    ),
}
print("PALLAS_STEP_JSON " + json.dumps(deltas))
"""


def _pallas_step_parity() -> dict | None:
    """Max relative dense-vs-Pallas deviation of the fused solve/projection
    step (5-step trajectory, ops/pallas_step.py) per layout, floored by the
    physical-field scale — merged into PARITY.json under ``"pallas_step"``."""
    return _parity_probe(
        _PALLAS_STEP_CHILD, "PALLAS_STEP_JSON ", "pallas_step", "max_rel_diff"
    )


_STATS_CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %(repo)r)
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu import Navier2D, Statistics
from rustpde_mpi_tpu.config import StatsConfig

def build():
    m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
    m.set_velocity(0.1, 1.0, 1.0)
    m.set_temperature(0.1, 1.0, 1.0)
    return m

m = build()
m.set_stats(StatsConfig(stride=3))
m.update_n(12)
twin = build()
legacy = Statistics(twin, 0.01, 1.0)
for _ in range(4):
    twin.update_n(3)
    legacy.update(twin)
n = float(np.asarray(m.stats_state.samples).reshape(-1)[0])
deltas = {}
for eng, leg in (
    ("t_sum", "t_avg"), ("ux_sum", "ux_avg"),
    ("uy_sum", "uy_avg"), ("nusselt_sum", "nusselt"),
):
    a = np.asarray(getattr(m.stats_state, eng)) / n
    b = np.asarray(getattr(legacy, leg))
    deltas[eng[:-4]] = float(np.abs(a - b).max() / (np.abs(b).max() or 1.0))
print("STATS_JSON " + json.dumps(deltas))
"""


def _stats_parity() -> dict | None:
    """Engine-vs-eager-legacy accumulator parity (max relative deviation
    of the running averages over a matched sampled trajectory), merged
    into PARITY.json under ``"stats"``."""
    return _parity_probe(_STATS_CHILD, "STATS_JSON ", "stats", "max_rel_diff")


_METRICS_CHILD = r"""
import json, os, sys, tempfile
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("RUSTPDE_X64", "1")
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu import Navier2D, ResilientRunner, telemetry
from rustpde_mpi_tpu.config import StabilityConfig

d = tempfile.mkdtemp()
m = Navier2D(17, 17, 1e4, 1.0, 0.01, 1.0, "rbc", periodic=False)
m.init_random(0.1, seed=0)
r = ResilientRunner(m, max_time=0.08, run_dir=os.path.join(d, "run"),
                    checkpoint_every_s=None, max_chunk_steps=4,
                    stability=StabilityConfig())
r.run()
print("METRICS_JSON " + json.dumps(telemetry.snapshot()))
"""


def _metrics_snapshot() -> dict | None:
    """Snapshot the telemetry registry of a tiny instrumented governed run
    (CPU child) into METRICS.json next to TESTS.json — the per-PR record
    of the live metric vocabulary (names, kinds, label sets), like the
    journal schema table but machine-readable.  Best-effort: a failure
    records the error string instead of killing the test record."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _METRICS_CHILD % {"repo": _REPO}],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=_REPO,
        )
        line = next(
            ln for ln in proc.stdout.splitlines()
            if ln.startswith("METRICS_JSON ")
        )
        snap = json.loads(line[len("METRICS_JSON "):])
    except Exception as exc:  # noqa: BLE001 — recording must not fail the run
        return {"error": f"{type(exc).__name__}: {exc}"}
    payload = {
        "names": {
            name: fam.get("kind", "?") for name, fam in sorted(snap.items())
        },
        "snapshot": snap,
        "date": _utc_now(),
    }
    path = os.path.join(_REPO, "METRICS.json")
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)
    # the TESTS.json row carries the compact inventory, not the full dump
    return {"names": payload["names"], "date": payload["date"]}


def _lint_payload() -> dict | None:
    """Run ``scripts/lint.py --json`` and persist the rule->count payload
    of both lint layers as LINT.json (project RPD rules + the curated
    GEN ruff-subset, engine recorded), with the baseline counts alongside
    so the delta is visible per-PR.  ``clean`` False (new findings or a
    stale baseline entry) fails the record run via rc=4.  Best-effort on
    infrastructure errors: the error string is recorded instead."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts", "lint.py"), "--json"],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=_REPO,
        )
        data = json.loads(proc.stdout)
    except Exception as exc:  # noqa: BLE001 — recording must not fail the run
        return {"error": f"{type(exc).__name__}: {exc}"}
    payload = {
        "engine": data.get("engine"),
        "files": data.get("files"),
        "counts": data.get("counts", {}),
        "baselined_counts": data.get("baselined_counts", {}),
        "suppressed": data.get("suppressed", 0),
        "stale": len(data.get("stale_baseline", [])),
        "new": len(data.get("new", [])),
        "clean": proc.returncode == 0,
        "date": _utc_now(),
    }
    path = os.path.join(_REPO, "LINT.json")
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)
    return payload


def _trend_payload() -> dict | None:
    """Run ``scripts/bench_trend.py --json --gate`` (writes TREND.json at
    the repo root, like LINT.json) and return the compact verdict.
    ``clean`` False — an un-acked perf regression against the rolling best
    — fails the record run via rc=5.  Best-effort on infrastructure
    errors: the error string is recorded instead."""
    try:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(_REPO, "scripts", "bench_trend.py"),
                "--json",
                "--gate",
            ],
            capture_output=True,
            text=True,
            timeout=120,
            cwd=_REPO,
        )
        data = json.loads(proc.stdout)
    except Exception as exc:  # noqa: BLE001 — recording must not fail the run
        return {"error": f"{type(exc).__name__}: {exc}"}
    return {
        "band": data.get("band"),
        "configs": len(data.get("configs", {})),
        "regressions": data.get("regressions", []),
        "regressions_unacked": data.get("regressions_unacked", []),
        "clean": proc.returncode == 0,
        "date": _utc_now(),
    }


def _utc_now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M UTC"
    )


def _persist(record: dict, tier_key: str) -> None:
    """Append ``record`` to TESTS.json, keeping SEPARATE fast-tier and
    full-tier sections (``{"fast": {latest, history}, "full": {...}}``): a
    stale full-tier ``latest`` used to shadow every later fast-tier run,
    so a tier-1 regression was invisible in the record.  The legacy
    top-level ``latest`` stays as "most recent run of any tier" for old
    readers; legacy flat histories are migrated by their tier string."""
    path = os.path.join(_REPO, "TESTS.json")
    try:
        with open(path) as f:
            prev = json.load(f)
    except (OSError, ValueError):
        prev = {}
    tiers = {}
    for key in ("fast", "full"):
        section = prev.get(key)
        tiers[key] = dict(section) if isinstance(section, dict) else {}
        tiers[key].setdefault("history", [])
    # one-time migration of the legacy flat history (entries carry a human
    # tier string: "fast" or "full (RUSTPDE_SLOW=1)")
    for entry in prev.get("history", []):
        key = "fast" if str(entry.get("tier", "")).startswith("fast") else "full"
        if entry not in tiers[key]["history"]:
            tiers[key]["history"].append(entry)
    tiers[tier_key]["latest"] = record
    tiers[tier_key]["history"] = (tiers[tier_key]["history"] + [record])[-10:]
    for key in ("fast", "full"):
        tiers[key].setdefault("latest", None)
    with open(path, "w") as f:
        json.dump({"latest": record, **tiers}, f, indent=1)


if __name__ == "__main__":
    sys.exit(main())
