#!/bin/bash
# Round-5 measurement runbook: run this when the axon relay comes back.
# Executes every queued on-chip measurement in dependency order and leaves
# the results in ./runbook_out/. Decisions (default flips) stay manual —
# read the A/B outputs against the gates in BASELINE.md "Round-5 changes".
#
# Usage: bash scripts/relay_runbook.sh [--quick]
#   --quick: skip the long legs (fast-synthesis validation, f64 profile)
set -u
cd "$(dirname "$0")/.."
OUT=runbook_out
mkdir -p "$OUT"
QUICK="${1:-}"

run() { # run <name> <timeout_s> <cmd...>
    local name=$1 to=$2; shift 2
    echo "=== $name ($(date +%H:%M:%S)) ==="
    timeout "$to" "$@" >"$OUT/$name.log" 2>&1
    echo "rc=$? -> $OUT/$name.log"
    tail -3 "$OUT/$name.log" | sed 's/^/    /'
}

# 0. probe
run probe 120 python -c "import jax; print(jax.devices())" || true
grep -q "axon\|Tpu" "$OUT/probe.log" || { echo "relay still down; aborting"; exit 1; }

# 1. full matrix at HEAD (warms the compile cache for everything below;
#    generous budget so no config rotates stale on this first post-outage run)
RUSTPDE_BENCH_BUDGET_S=1800 RUSTPDE_BENCH_SLACK_S=900 \
    run bench_full_1 2900 python bench.py

# 2. step-level A/Bs at the flagships (defaults off -> baseline numbers are
#    in bench_full_1; these runs measure the knobs ON)
ab() { # ab <name> <env=val> <call>
    local name=$1 env=$2 call=$3
    run "$name" 900 env $env python -c "import bench, json; print(json.dumps($call))"
}
ab ab_fwdprec_1025 "RUSTPDE_FWD_PRECISION=high" "bench.bench_navier(1025,1025,1e9,1e-4,64)"
ab ab_fwdprec_2049 "RUSTPDE_FWD_PRECISION=high" "bench.bench_navier(2049,2049,1e9,5e-5,16)"
ab ab_solveprec_1025 "RUSTPDE_SOLVE_PRECISION=high" "bench.bench_navier(1025,1025,1e9,1e-4,64)"
ab ab_solveprec_2049 "RUSTPDE_SOLVE_PRECISION=high" "bench.bench_navier(2049,2049,1e9,5e-5,16)"
ab ab_both_1025 "RUSTPDE_FWD_PRECISION=high RUSTPDE_SOLVE_PRECISION=high" "bench.bench_navier(1025,1025,1e9,1e-4,64)"
# periodic1024: sep layout on the Chebyshev axis vs default
ab ab_periodic_sep "RUSTPDE_SEP=1" "bench.bench_navier(1024,1025,1e9,1e-4,16,periodic=True)"
# periodic1024: fourstep vs circ-fold on the 1024 Fourier axis
ab ab_periodic_nofourstep "RUSTPDE_FOURSTEP=0" "bench.bench_navier(1024,1025,1e9,1e-4,16,periodic=True)"

# 3. f64 hybrid perf leg (writes F64_HYBRID_AB.json)
run hybrid_perf 3600 python scripts/ab_f64_hybrid.py --perf

if [ "$QUICK" != "--quick" ]; then
    # 4. long-horizon fast-synthesis statistics artifact
    run fast_synth 3600 python scripts/validate_fast_synthesis.py
    # 5. f64 component profile at the flagship (VERDICT r4 next #3a)
    run profile_f64_2049 3600 env RUSTPDE_X64=1 python scripts/profile_step.py --n 2049 --iters 2
    # 6. shadow-gated full matrix again at defaults: the recorded state the
    #    driver capture will reproduce (all-fresh, zero stale)
    RUSTPDE_BENCH_BUDGET_S=900 RUSTPDE_BENCH_SLACK_S=600 \
        run bench_full_2 1600 python bench.py
fi

echo "=== runbook done ($(date +%H:%M:%S)); results in $OUT/ ==="
