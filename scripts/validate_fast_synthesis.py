"""Long-horizon validation of the fast (3-pass bf16) convection-synthesis
default (VERDICT r4 weak #5: the default-precision choice must rest on a
committed, reproducible artifact, not prose).

Reruns the 4096-step Ra=1e9 f32 comparison that justified defaulting
``RUSTPDE_SYNTH_PRECISION=high``: two identical 1025^2 trajectories from the
same deterministic IC, one with the fast synthesis variants, one forced to
"highest", and writes their Re/Nu/Nuvol/|div| statistics to
``FAST_SYNTH_VALIDATION.json`` at the repo root, next to BENCH_FULL.json.

Each variant runs in its own subprocess: the synthesis-precision env is read
at operator-build time and Base instances are interned process-wide
(bases._BASE_CACHE), so toggling the env inside one process would alias the
("bwd","fast") device matrices between variants.

The short-horizon shadow gate (bench.py) bounds per-step numerics; this
script bounds the *statistics* over a long chaotic stretch — pointwise fields
decorrelate (positive Lyapunov), so the gates compare windowed means:
mean Re and mean Nu over the second half must agree to the thresholds below,
and both runs must stay finite with decaying |div|.

Usage:  python scripts/validate_fast_synthesis.py [--steps 4096] [--n 1025]
        (TPU: ~25 s of stepping per variant at ~700 steps/s + compile)
"""

import argparse
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# windowed-mean agreement gates (second half of the trajectory).  Over 0.4
# time units from the identical IC the trajectories have not fully
# decorrelated (measured r4: Re agreed to 4 digits), but the gates are set an
# order looser so the artifact tests the numerics, not the chaos.
GATE_RE_REL = 1e-2
GATE_NU_REL = 2e-2


def run_variant(synth: str, n: int, steps: int, chunk: int) -> dict:
    env = dict(os.environ, RUSTPDE_X64="0", RUSTPDE_SYNTH_PRECISION=synth)
    code = (
        "import sys; sys.path.insert(0, {repo!r})\n"
        "import json, os\n"
        "import jax\n"
        "# sitecustomize forces jax_platforms programmatically; honor an\n"
        "# explicit JAX_PLATFORMS=cpu (tests/conftest.py dance)\n"
        "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
        "    jax.config.update('jax_platforms', 'cpu')\n"
        "from rustpde_mpi_tpu import Navier2D, config\n"
        "config.enable_compilation_cache()\n"
        "model = Navier2D.new_confined({n}, {n}, 1e9, 1.0, 1e-4, 1.0, 'rbc')\n"
        "model.set_velocity(0.1, 2.0, 2.0)\n"
        "model.set_temperature(0.1, 2.0, 2.0)\n"
        "rows = []\n"
        "done = 0\n"
        "while done < {steps}:\n"
        "    k = min({chunk}, {steps} - done)\n"
        "    model.update_n(k)\n"
        "    done += k\n"
        "    nu, nuvol, re, div = model.get_observables()\n"
        "    rows.append({{'step': done, 'nu': nu, 'nuvol': nuvol,"
        " 're': re, 'div': div}})\n"
        "print(json.dumps(rows))\n"
    ).format(repo=_REPO, n=n, steps=steps, chunk=chunk)
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=3600,
        cwd=_REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(f"variant {synth} failed:\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def stats(rows: list[dict]) -> dict:
    half = rows[len(rows) // 2 :]
    mean = lambda key, rs: sum(r[key] for r in rs) / len(rs)
    return {
        "n_samples": len(rows),
        "re_mean_2nd_half": mean("re", half),
        "nu_mean_2nd_half": mean("nu", half),
        "nuvol_mean_2nd_half": mean("nuvol", half),
        "div_final": rows[-1]["div"],
        "div_max": max(r["div"] for r in rows),
        "finite": all(
            v == v for r in rows for v in (r["nu"], r["re"], r["div"])
        ),
        "series": rows,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4096)
    ap.add_argument("--n", type=int, default=1025)
    ap.add_argument("--chunk", type=int, default=512)
    args = ap.parse_args()

    result: dict = {"config": vars(args) | {"ra": 1e9, "dt": 1e-4, "x64": False}}
    for synth in ("highest", "high"):
        print(f"# running {args.steps} steps with RUSTPDE_SYNTH_PRECISION={synth}")
        result[synth] = stats(run_variant(synth, args.n, args.steps, args.chunk))
        s = result[synth]
        print(
            f"#   Re={s['re_mean_2nd_half']:.6g} Nu={s['nu_mean_2nd_half']:.6g} "
            f"div_final={s['div_final']:.3g} finite={s['finite']}"
        )

    hi, fa = result["highest"], result["high"]
    re_rel = abs(fa["re_mean_2nd_half"] - hi["re_mean_2nd_half"]) / abs(
        hi["re_mean_2nd_half"]
    )
    nu_rel = abs(fa["nu_mean_2nd_half"] - hi["nu_mean_2nd_half"]) / abs(
        hi["nu_mean_2nd_half"]
    )
    result["comparison"] = {
        "re_rel": re_rel,
        "nu_rel": nu_rel,
        "gate_re_rel": GATE_RE_REL,
        "gate_nu_rel": GATE_NU_REL,
        "passed": bool(
            re_rel < GATE_RE_REL
            and nu_rel < GATE_NU_REL
            and hi["finite"]
            and fa["finite"]
        ),
    }
    # repo root, next to BENCH_FULL.json (data/ is gitignored and this
    # artifact is the committed evidence for the default-precision choice)
    out_path = os.path.join(_REPO, "FAST_SYNTH_VALIDATION.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(
        f"re_rel={re_rel:.3g} nu_rel={nu_rel:.3g} "
        f"passed={result['comparison']['passed']} -> {out_path}"
    )
    return 0 if result["comparison"]["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
