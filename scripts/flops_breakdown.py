"""Exact per-component dot_general FLOPs of one Navier2D step (trace-only).

Answers "which GEMM family dominates the step" without running anything:
every component is traced with jax.make_jaxpr and its dot_general flops
summed (utils/profiling._jaxpr_dot_flops — the same counter the MFU
estimate uses).  This is the *algebraic* decomposition; wall-time shares
additionally depend on per-op efficiency (f64 emulation factors, GEMM
shapes), which scripts/profile_step.py measures on-chip.

Usage:  [RUSTPDE_X64=1] python scripts/flops_breakdown.py [--n 2049]
        [--periodic] [--nx 1024]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=2049)
    ap.add_argument("--nx", type=int, default=None, help="periodic x size")
    ap.add_argument("--periodic", action="store_true")
    args = ap.parse_args()

    # trace on CPU unconditionally: make_jaxpr itself executes nothing, but
    # the operator-constant placement (bases._dev ensure_compile_time_eval)
    # DOES run device transfers — on the axon backend that hangs when the
    # relay is down, and this script never needs the chip
    os.environ.setdefault("RUSTPDE_FORCE_TPU_PATH", "1")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from rustpde_mpi_tpu import Navier2D, config
    from rustpde_mpi_tpu.utils.profiling import _jaxpr_dot_flops

    n = args.n
    nx = args.nx or (n - 1 if args.periodic else n)
    model = Navier2D(
        nx, n, 1e9, 1.0, 1e-4 if n <= 1025 else 5e-5, 1.0, "rbc",
        periodic=args.periodic,
    )
    print(
        f"n={nx}x{n} periodic={args.periodic} "
        f"x64={config.X64} sep={model.temp_space.sep}"
    )

    def flops(fn, *ex):
        return _jaxpr_dot_flops(jax.make_jaxpr(fn)(*ex).jaxpr)

    st = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), model.state
    )
    total = flops(model._make_step(), st)

    sp_t, sp_u, sp_v = model.temp_space, model.velx_space, model.vely_space
    sp_f, sp_p, sp_q = model.field_space, model.pres_space, model.pseu_space
    scale = model.scale
    ex = {
        "t": st.temp, "u": st.velx, "v": st.vely, "p": st.pres, "q": st.pseu,
        "phys": jax.ShapeDtypeStruct(sp_f.shape_physical, config.real_dtype()),
        "ortho": jax.ShapeDtypeStruct(
            (sp_f.shape_spectral if not args.periodic else sp_f.shape_spectral),
            config.real_dtype() if not sp_f.spectral_is_complex else sp_f.spectral_dtype(),
        ),
    }

    rows = []

    def rec(name, fl, count=1):
        rows.append((name, fl * count))
        pct = 100.0 * fl * count / total if total else 0.0
        print(f"{name:46s} {fl * count / 1e9:9.2f} GF  {pct:5.1f}%")

    print(f"{'FULL STEP':46s} {total / 1e9:9.2f} GF  100.0%")
    # convection-chain syntheses (the hybrid/fast-key family)
    rec(
        "conv syntheses: 2x backward_fast(vel)",
        flops(lambda a: sp_u.backward_fast(a), ex["u"])
        + flops(lambda a: sp_v.backward_fast(a), ex["v"]),
    )
    bg = 0.0
    for sp, e in ((sp_u, ex["u"]), (sp_v, ex["v"]), (sp_t, ex["t"])):
        for d in ((1, 0), (0, 1)):
            bg += flops(
                lambda a, _sp=sp, _d=d: _sp.backward_gradient(a, _d, scale, fast=True),
                e,
            )
    rec("conv syntheses: 6x backward_gradient", bg)
    try:
        fd = flops(lambda a: sp_f.forward_dealiased(a, fast=True), ex["phys"])
    except ValueError:
        fd = flops(lambda a: sp_f.forward(a), ex["phys"])
    rec("conv forwards: 3x forward_dealiased", fd, 3)
    # implicit solves: rhs lives in the ORTHO (field) space, like the step's
    # to_ortho/conv outputs
    ortho_ex = jax.ShapeDtypeStruct(
        sp_f.shape_spectral,
        config.real_dtype() if not sp_f.spectral_is_complex
        else sp_f.spectral_dtype(),
    )
    so = sum(
        flops(sol.solve, ortho_ex)
        for sol in (model.solver_velx, model.solver_vely, model.solver_temp)
    )
    rec("3x ADI Helmholtz solve", so)
    rec("Poisson solve (pseudo-pressure)", flops(model.solver_pres.solve, ortho_ex))
    # gradients / projection
    g = flops(lambda a: sp_p.gradient(a, (1, 0), scale), ex["p"]) + flops(
        lambda a: sp_p.gradient(a, (0, 1), scale), ex["p"]
    )
    rec("2x pres gradient (rhs)", g)
    g = flops(lambda a: sp_u.gradient(a, (1, 0), scale), ex["u"]) + flops(
        lambda a: sp_v.gradient(a, (0, 1), scale), ex["v"]
    )
    rec("divergence (2 gradients)", g)
    if model._proj_grad is not None:
        gx0, gx1, gy0, gy1 = model._proj_grad
        ax = 0
        rec(
            "projection correction (fused proj-grad)",
            flops(lambda a: gx1.apply(gx0.apply(a, ax), ax + 1), ex["q"])
            + flops(lambda a: gy1.apply(gy0.apply(a, ax), ax + 1), ex["q"]),
        )
    accounted = sum(f for _, f in rows)
    print(
        f"{'(other: stencils, to/from_ortho, obs-free)':46s} "
        f"{(total - accounted) / 1e9:9.2f} GF  {100.0 * (total - accounted) / total:5.1f}%"
    )
    conv = sum(f for name, f in rows if name.startswith("conv"))
    print(
        f"\nconvection-transform family (hybrid/fast-key target): "
        f"{100.0 * conv / total:.1f}% of step dot-flops"
    )


if __name__ == "__main__":
    main()
