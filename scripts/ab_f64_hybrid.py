"""A/B the f64 hybrid (RUSTPDE_F64_HYBRID=1: f32 convection transforms
feeding f64 solves — SURVEY S7, VERDICT r4 next #3b) against pure f64.

Two legs, each isolated in subprocesses (the sep-operator cache is built
from the env once per process):

* ``--parity`` (CPU-safe): the PARITY.json flagship trajectory (129^2
  Ra=1e7, 500 steps) run on the forced TPU path with and without the
  hybrid; reports the per-sample relative Nu drift hybrid-vs-pure.  The
  f32 budget for this statistic is ~3e-5 (PARITY.json max_drift); the
  hybrid must not exceed that scale, since its only degradation is f32
  convection roundoff.
* ``--perf`` (TPU): slope-timed step rates of the two f64 flagships
  (1025^2, 2049^2) with hybrid off/on, via bench.bench_navier in X64
  subprocesses.  Does NOT touch BENCH_FULL.json.

Writes F64_HYBRID_AB.json at the repo root (legs merge across runs).
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PARITY_CHILD = r"""
import json, os, sys
sys.path.insert(0, %(repo)r)
import jax
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu import Navier2D, config
config.enable_compilation_cache()
model = Navier2D(129, 129, 1e7, 1.0, 2e-3, 1.0, "rbc", periodic=False)
model.init_random(0.01, seed=0)
rows = []
for _ in range(10):
    model.update_n(50)
    nu, nuvol, re, div = model.get_observables()
    rows.append({"time": round(model.time, 10), "nu": nu, "re": re, "div": div})
print("ROWS:" + json.dumps(rows))
"""


def _child(code: str, extra_env: dict, timeout: int = 3600) -> str:
    env = dict(os.environ, **extra_env)
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=timeout,
        cwd=REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-3000:])
    return out.stdout


def run_parity(cpu: bool) -> dict:
    rows = {}
    for hybrid in ("0", "1"):
        env = {
            "RUSTPDE_X64": "1",
            "RUSTPDE_FORCE_TPU_PATH": "1",
            "RUSTPDE_F64_HYBRID": hybrid,
        }
        if cpu:
            env["JAX_PLATFORMS"] = "cpu"
        out = _child(PARITY_CHILD % {"repo": REPO}, env)
        line = next(l for l in out.splitlines() if l.startswith("ROWS:"))
        rows[hybrid] = json.loads(line[5:])
    drift = [
        abs(h["nu"] - p["nu"]) / abs(p["nu"])
        for h, p in zip(rows["1"], rows["0"])
    ]
    return {
        "pure": rows["0"],
        "hybrid": rows["1"],
        "nu_drift": drift,
        "max_nu_drift": max(drift),
        "f32_budget": 3e-5,
        "passed": max(drift) < 3e-5,
        "platform": "cpu" if cpu else "tpu",
    }


def run_perf() -> dict:
    res: dict = {}
    for name, call in (
        ("rbc1025_f64", "bench.bench_navier(1025,1025,1e9,1e-4,16)"),
        ("rbc2049_f64", "bench.bench_navier(2049,2049,1e9,5e-5,4)"),
    ):
        res[name] = {}
        for hybrid in ("0", "1"):
            code = f"import bench, json; print(json.dumps({call}))"
            out = _child(
                code, {"RUSTPDE_X64": "1", "RUSTPDE_F64_HYBRID": hybrid}
            )
            r = json.loads(out.strip().splitlines()[-1])
            res[name]["hybrid" if hybrid == "1" else "pure"] = {
                k: r[k]
                for k in ("steps_per_sec", "ms_per_step", "nu", "finite")
                if k in r
            }
            print(f"# {name} hybrid={hybrid}: {r['steps_per_sec']:.1f} steps/s")
        a = res[name]["pure"]["steps_per_sec"]
        b = res[name]["hybrid"]["steps_per_sec"]
        res[name]["speedup"] = b / a
    return res


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--parity", action="store_true")
    ap.add_argument("--perf", action="store_true")
    ap.add_argument("--cpu", action="store_true", help="parity leg on CPU")
    args = ap.parse_args()
    if not (args.parity or args.perf):
        args.parity = args.perf = True

    path = os.path.join(REPO, "F64_HYBRID_AB.json")
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, ValueError):
        record = {}
    if args.parity:
        record["parity"] = run_parity(args.cpu)
        print(
            f"parity: max Nu drift hybrid-vs-pure = "
            f"{record['parity']['max_nu_drift']:.3e} "
            f"(budget 3e-5, passed={record['parity']['passed']})"
        )
    if args.perf:
        record["perf"] = run_perf()
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {path}")
    ok = record.get("parity", {}).get("passed", True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
