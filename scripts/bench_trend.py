"""Parse the BENCH_r*.json / BENCH_FULL.json history into per-config metric
trajectories and gate on regressions — the piece that turns the pile of
bench round files into a machine-checked trend instead of archaeology.

Usage::

    python scripts/bench_trend.py                 # write TREND.json, report
    python scripts/bench_trend.py --json          # machine payload on stdout
    python scripts/bench_trend.py --gate          # rc=5 on un-acked regression
    python scripts/bench_trend.py --ack rbc1025 --reason "relay degraded, \\
        tracked in ROADMAP"                       # accept the latest point

How it reads the history:

* every ``BENCH_r*.json`` round file carries the driver's ``parsed`` final
  JSON line (flagship ``value`` + optional per-config ``configs`` rows); a
  round whose ``parsed`` is null is re-parsed from the recorded ``tail``
  and skipped when unrecoverable (rc!=0 rounds),
* ``BENCH_FULL.json`` (``results`` per config) is the newest point,
* per config the primary metric is ``member_steps_per_sec`` (serve rows)
  else ``steps_per_sec`` else the flagship ``value``; rows marked
  ``stale`` (budget-starved carry-overs) are excluded.

The gate: a config REGRESSES when its newest point falls below
``(1 - band) * rolling_best`` of all earlier points (band from
``RUSTPDE_TREND_BAND``, default 0.3 — the axon relay's measured round-to-
round weather sits well inside that).  Regressions must be ACKED with a
written reason (``--ack``) to pass the gate; acks pin (config, round,
MEASURED VALUE) — a later round, or a re-captured point at a different
value (BENCH_FULL's label never changes), re-fires the gate.
``scripts/record_tests.py`` runs this with ``--gate`` and fails the
record run (rc=5) on an un-acked regression, the same way LINT.json
already gates.
"""

import argparse
import datetime
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: preference order for a config row's primary metric
_PRIMARY = ("member_steps_per_sec", "steps_per_sec")


def _primary_metric(row: dict):
    for name in _PRIMARY:
        v = row.get(name)
        if isinstance(v, (int, float)) and v > 0:
            return name, float(v)
    return None, None


def _last_json_line(text: str):
    """Best-effort recovery of the driver's final JSON line from a recorded
    ``tail`` (the round file truncates output from the FRONT, so the final
    line is usually intact)."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _round_configs(parsed: dict) -> dict:
    """``{config: {"metric", "value"}}`` from one round's parsed payload."""
    out = {}
    if isinstance(parsed.get("value"), (int, float)):
        out["flagship"] = {
            "metric": parsed.get("unit", "steps/s"),
            "value": float(parsed["value"]),
        }
    for name, row in (parsed.get("configs") or {}).items():
        if not isinstance(row, dict) or row.get("stale"):
            continue
        metric, value = _primary_metric(row)
        if metric is not None:
            out[name] = {"metric": metric, "value": value}
    return out


def collect_history(repo: str = _REPO) -> list:
    """Ordered ``[(label, {config: {"metric","value"}}), ...]``: the
    BENCH_rNN rounds by number, then BENCH_FULL as the newest point."""
    points = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        label = os.path.splitext(os.path.basename(path))[0].replace("BENCH_", "")
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            continue
        parsed = data.get("parsed")
        if not isinstance(parsed, dict):
            parsed = _last_json_line(data.get("tail", ""))
        if not isinstance(parsed, dict):
            continue  # unrecoverable round (rc!=0, torn tail)
        configs = _round_configs(parsed)
        if configs:
            points.append((label, configs))
    full_path = os.path.join(repo, "BENCH_FULL.json")
    try:
        with open(full_path, encoding="utf-8") as fh:
            results = json.load(fh).get("results", {})
    except (OSError, ValueError):
        results = {}
    configs = {}
    for name, row in results.items():
        if not isinstance(row, dict) or row.get("stale"):
            continue
        metric, value = _primary_metric(row)
        if metric is not None:
            configs[name] = {"metric": metric, "value": value}
    if configs:
        points.append(("full", configs))
    return points


def compute_trend(points: list, band: float, acks: dict | None = None) -> dict:
    """The TREND.json payload: per-config trajectory, rolling best, the
    regression verdict against the noise band, and ack status."""
    acks = acks or {}
    by_config: dict[str, list] = {}
    for label, configs in points:
        for name, entry in configs.items():
            by_config.setdefault(name, []).append(
                {"label": label, "value": entry["value"], "metric": entry["metric"]}
            )
    trend = {}
    regressions, unacked = [], []
    for name, series in sorted(by_config.items()):
        latest = series[-1]
        earlier = [p["value"] for p in series[:-1]]
        best = max(earlier) if earlier else latest["value"]
        ratio = latest["value"] / best if best > 0 else 1.0
        regressed = len(series) >= 2 and latest["value"] < (1.0 - band) * best
        ack = acks.get(name)
        # an ack pins (config, round, MEASURED VALUE): BENCH_FULL's label
        # is always "full", so without the value fingerprint one ack there
        # would silence every future regression of that config forever — a
        # re-captured point with a different value must re-fire the gate
        acked = bool(
            regressed
            and ack
            and ack.get("label") == latest["label"]
            and ack.get("value") is not None
            and abs(latest["value"] - ack["value"])
            <= 1e-9 * max(abs(latest["value"]), abs(ack["value"]), 1e-30)
        )
        trend[name] = {
            "points": series,
            "metric": latest["metric"],
            "rolling_best": best,
            "latest": latest["value"],
            "latest_label": latest["label"],
            "ratio": round(ratio, 4),
            "regressed": regressed,
            "acked": acked,
            **({"ack": ack} if acked else {}),
        }
        if regressed:
            regressions.append(name)
            if not acked:
                unacked.append(name)
    return {
        "band": band,
        "configs": trend,
        "regressions": regressions,
        "regressions_unacked": unacked,
        "acks": acks,
        "date": datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%d %H:%M UTC"
        ),
    }


def _load_acks(out_path: str) -> dict:
    """Acks persist inside TREND.json itself — one artifact, no side file."""
    try:
        with open(out_path, encoding="utf-8") as fh:
            return json.load(fh).get("acks", {}) or {}
    except (OSError, ValueError):
        return {}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=_REPO, help="repo root to scan")
    ap.add_argument("--out", default=None, help="output path (default <repo>/TREND.json)")
    ap.add_argument("--band", type=float, default=None,
                    help="noise band (default RUSTPDE_TREND_BAND or 0.3)")
    ap.add_argument("--json", action="store_true", help="print the payload")
    ap.add_argument("--gate", action="store_true",
                    help="exit 5 when an un-acked regression is present")
    ap.add_argument("--ack", default=None, metavar="CONFIG",
                    help="ack CONFIG's latest point as accepted")
    ap.add_argument("--reason", default=None,
                    help="written reason for --ack (required with it)")
    args = ap.parse_args(argv)

    out_path = args.out or os.path.join(args.repo, "TREND.json")
    band = args.band
    if band is None:
        band = float(os.environ.get("RUSTPDE_TREND_BAND", "0.3") or 0.3)

    acks = _load_acks(out_path)
    points = collect_history(args.repo)
    payload = compute_trend(points, band, acks)

    if args.ack:
        if not args.reason:
            print("--ack requires --reason <written why>", file=sys.stderr)
            return 2
        cfg = payload["configs"].get(args.ack)
        if cfg is None:
            print(f"unknown config {args.ack!r}; known: "
                  f"{sorted(payload['configs'])}", file=sys.stderr)
            return 2
        acks[args.ack] = {
            "label": cfg["latest_label"],
            "value": cfg["latest"],
            "reason": args.reason,
            "date": payload["date"],
        }
        payload = compute_trend(points, band, acks)

    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, out_path)

    if args.json:
        print(json.dumps(payload))
    else:
        for name, cfg in payload["configs"].items():
            flag = ""
            if cfg["regressed"]:
                flag = " ACKED" if cfg["acked"] else " REGRESSED"
            print(
                f"{name:24s} {cfg['latest']:>12.3f} {cfg['metric']:<22s}"
                f" best {cfg['rolling_best']:>12.3f} ratio {cfg['ratio']:.3f}"
                f"{flag}"
            )
        if payload["regressions_unacked"]:
            print(f"UN-ACKED regressions: {payload['regressions_unacked']}")
    if args.gate and payload["regressions_unacked"]:
        return 5
    return 0


if __name__ == "__main__":
    sys.exit(main())
