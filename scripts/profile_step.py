"""Component-level timing of the confined Navier2D step (VERDICT r2 #5).

Times each building block of the 1025^2 step in isolation — transforms
(dense vs four-step), derivatives (GEMM vs cumsum), banded applies, ADI and
Poisson solves, and the full step — each as a jitted scan with a readback
sync (the axon relay does not honor block_until_ready, utils/profiling.py).

Usage:  [RUSTPDE_X64=0] python scripts/profile_step.py [--n 1025] [--iters 50]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, state, iters):
    """Per-iteration ms via a two-point slope: time scans of length iters and
    4*iters and divide the difference — the axon relay's fixed per-dispatch
    cost (hundreds of ms) cancels, leaving pure device time."""
    import functools

    import jax
    import numpy as np

    def body(c, _):
        return fn(c), None

    @functools.partial(jax.jit, static_argnames=("length",))
    def run(s, length):
        return jax.lax.scan(body, s, None, length=length)[0]

    def once(length):
        out = run(state, length)
        leaf = jax.tree.leaves(out)[0]
        np.asarray(leaf[(0,) * leaf.ndim])  # 1-element readback: slicing on
        # device first -- np.asarray(whole) would stream MBs through the
        # relay and its transfer-time variance swamps the timing

    times = {}
    for length in (iters, 4 * iters):
        once(length)  # compile + warm
        best = float("inf")
        for _ in range(3):  # min-of-3: the relay adds 10-30% run noise
            t0 = time.perf_counter()
            once(length)
            best = min(best, time.perf_counter() - t0)
        times[length] = best
    return (times[4 * iters] - times[iters]) / (3 * iters) * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1025)
    ap.add_argument("--iters", type=int, default=50)
    args = ap.parse_args()

    os.environ.setdefault("RUSTPDE_X64", "0")
    import jax.numpy as jnp
    import numpy as np

    from rustpde_mpi_tpu import Navier2D, config
    from rustpde_mpi_tpu.ops import fourstep

    n = args.n
    it = args.iters
    rdt = config.real_dtype()
    print(f"platform={config.default_device_kind()} n={n} dtype={np.dtype(rdt).name}")

    model = Navier2D(n, n, 1e9, 1.0, 1e-4, 1.0, "rbc", periodic=False)
    model.init_random(0.1)
    sp_f = model.field_space
    sp_u = model.velx_space
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.standard_normal((n, n)), dtype=rdt)
    rows = []

    def rec(name, ms):
        rows.append((name, ms))
        print(f"{name:42s} {ms:8.3f} ms")

    # full step
    step = model._make_step()
    from rustpde_mpi_tpu.utils.jit import hoist_constants

    step_cc, consts = hoist_constants(step, model.state)
    rec("full step", timeit(lambda s: step_cc(consts, s), model.state, it))

    # transforms: pure-space forward+backward_ortho pair (fast path auto)
    rec(
        "field fwd+bwd_ortho (fast DCT pair)",
        timeit(lambda a: sp_f.backward_ortho(sp_f.forward(a)), v, it),
    )
    base = sp_f.base_x

    def dense_pair(a):
        c = base._fwd_matrix.apply(base._fwd_matrix.apply(a, 0), 1)
        return base._synthesis_dev.apply(base._synthesis_dev.apply(c, 0), 1)

    rec("dense folded DCT pair (2 axes each way)", timeit(dense_pair, v, it))
    if base._dct_plan is not None:

        def fast_pair(a):
            c = base._fast_analysis(base._fast_analysis(a, 0), 1)
            return base._fast_synthesis(base._fast_synthesis(c, 0), 1)

        rec("fourstep DCT pair (2 axes each way)", timeit(fast_pair, v, it))

    # derivative: cumsum vs checker GEMM vs the sep trapezoid strips
    from rustpde_mpi_tpu.ops import transforms as tr

    rec("cheb_derivative cumsum (1 axis)", timeit(lambda a: tr.cheb_derivative(a, 1, 0), v, it))
    gm = base._gradient_dev(1)
    rec("gradient checker GEMM (1 axis)", timeit(lambda a: gm.apply(a, 0), v, it))
    if any(sp_u.sep):
        m_c = sp_u.base_x.m
        gs = sp_u.base_x._sep_dev(("grad", 1))
        vu_g = jnp.asarray(rng.standard_normal((m_c, n)), dtype=rdt)
        rec(
            f"gradient sep ({gs.kind}) (1 axis)",
            timeit(lambda a: gs.apply(a, 0)[:m_c], vu_g, it),
        )
        bg = sp_u.base_x._sep_dev(("bwd_grad", 1))
        rec(
            "bwd_grad fused synthesis-of-derivative (1 axis)",
            timeit(lambda a: bg.apply(a, 0)[:m_c], vu_g, it),
        )
    if all(sp_f.sep):
        rec(
            "forward_dealiased (2 axes, rows dropped)",
            timeit(sp_f.forward_dealiased, v, it),
        )

    # banded apply vs what it replaced (slice keeps the scan carry shape)
    st = sp_u.base_x._stencil_dev
    m_u = sp_u.base_x.m
    vu = jnp.asarray(rng.standard_normal((m_u, n)), dtype=rdt)
    rec(
        f"banded stencil apply ({st.kind})",
        timeit(lambda a: st.apply(a, 0)[:m_u], vu, it),
    )

    # solves: rhs is ortho-space (n rows per axis), solution composite (m) —
    # pad back to the carry shape
    rhs_u = jnp.asarray(rng.standard_normal((n, n)), dtype=rdt)

    def adi(a):
        out = model.solver_velx.solve(a)
        return jnp.pad(out, ((0, n - out.shape[0]), (0, n - out.shape[1])))

    rec("HholtzAdi solve (velx)", timeit(adi, rhs_u, it))

    def poi(a):
        out = model.solver_pres.solve(a)
        return jnp.pad(out, ((0, n - out.shape[0]), (0, n - out.shape[1])))

    rec("Poisson FastDiag solve", timeit(poi, rhs_u, it))

    # raw GEMM reference point: one folded dense transform-sized matmul
    big = base._synthesis_dev
    rec("single dense synthesis GEMM (1 axis)", timeit(lambda a: big.apply(a, 0), v, it))

    full = rows[0][1]
    print(f"\ncomponents sum context: full step = {full:.3f} ms")


if __name__ == "__main__":
    main()
