"""Record the Nu-parity artifact: f64 golden trajectory + f32 drift.

Config is the reference's flagship serial run
(/root/reference/src/main.rs:37-58): confined RBC, 129x129, Ra=1e7, Pr=1,
dt=2e-3, amp-0.01 random IC (seeded here for reproducibility).

Writes PARITY.json at the repo root:

* ``nu_f64``: Nusselt/Nuvol/Re/|div| at each sample step on the f64 CPU
  banded path (the parity gold for tests/test_parity.py),
* ``nu_f32``: same trajectory on the f32 path, and ``drift``: the relative
  Nu deviation |Nu32 - Nu64| / |Nu64| per sample — the recorded answer to
  "does the f32 TPU trajectory track the f64 one" (VERDICT r1 weak #10).

Run from the repo root: ``python scripts/record_parity.py``.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG = {
    "nx": 129,
    "ny": 129,
    "ra": 1e7,
    "pr": 1.0,
    "dt": 2e-3,
    "aspect": 1.0,
    "bc": "rbc",
    "amp": 0.01,
    "sample_every": 50,
    "samples": 10,
}

_CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from rustpde_mpi_tpu import Navier2D

cfg = json.loads(%(cfg)r)
model = Navier2D(cfg["nx"], cfg["ny"], cfg["ra"], cfg["pr"], cfg["dt"],
                 cfg["aspect"], cfg["bc"], periodic=False)
model.init_random(cfg["amp"], seed=0)
rows = []
for _ in range(cfg["samples"]):
    model.update_n(cfg["sample_every"])
    nu, nuvol, re, div = model.get_observables()
    rows.append({"time": round(model.time, 10), "nu": nu, "nuvol": nuvol,
                 "re": re, "div": div})
print("ROWS:" + json.dumps(rows))
"""


def run_trajectory(x64: bool):
    env = dict(os.environ)
    env["RUSTPDE_X64"] = "1" if x64 else "0"
    env["JAX_PLATFORMS"] = "cpu"
    code = _CHILD % {"repo": REPO, "cfg": json.dumps(CONFIG)}
    res = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True,
        timeout=3600, check=False,
    )
    if res.returncode != 0:
        sys.stderr.write(res.stderr)
        raise RuntimeError(f"trajectory run (x64={x64}) failed")
    for line in res.stdout.splitlines():
        if line.startswith("ROWS:"):
            return json.loads(line[len("ROWS:"):])
    raise RuntimeError("no ROWS line in child output")


def main() -> None:
    f64 = run_trajectory(x64=True)
    f32 = run_trajectory(x64=False)
    drift = [
        abs(a["nu"] - b["nu"]) / max(abs(b["nu"]), 1e-300)
        for a, b in zip(f32, f64)
    ]
    out = {
        "config": CONFIG,
        "platform": "cpu",
        "note": (
            "f64 banded-path golden trajectory for the reference flagship "
            "config (main.rs:37-58); f32 drift = |Nu32-Nu64|/Nu64 per sample"
        ),
        "nu_f64": f64,
        "nu_f32": f32,
        "drift": drift,
        "max_drift": max(drift),
    }
    path = os.path.join(REPO, "PARITY.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(out, fh, indent=1)
    print(f"wrote {path}; max f32 Nu drift = {max(drift):.3e}")


if __name__ == "__main__":
    main()
