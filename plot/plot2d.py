"""Plot one flow snapshot: temperature + streamlines (+ vorticity/mask).

Counterpart of the reference's plot/plot2d.py over the same HDF5 snapshot
layout.  Non-interactive by default: pass --index/--file (the reference asks
on stdin); --list shows the sorted snapshot inventory.

    python plot/plot2d.py --index -1 --out fig.png
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from plot_utils import (  # noqa: E402
    plot_contour,
    plot_streamplot,
    read_snapshot_fields,
    sorted_snapshots,
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--file", help="snapshot .h5 (overrides --index)")
    ap.add_argument("--index", type=int, default=-1, help="index into the sorted list")
    ap.add_argument("--list", action="store_true", help="list snapshots and exit")
    ap.add_argument("--out", default="fig.png")
    ap.add_argument("--show", action="store_true")
    ap.add_argument("--vorticity", action="store_true", help="also plot vorticity")
    args = ap.parse_args()

    files = sorted_snapshots()
    if args.list:
        for i, f in enumerate(files):
            print(f"# {i:3d}: {f}")
        return 0
    filename = args.file or (files[args.index] if files else None)
    if filename is None:
        print("no snapshots found (*.h5, data/*.h5)")
        return 1

    d = read_snapshot_fields(filename)
    total_temp = d["temp"] + (d["tempbc"] if d["tempbc"] is not None else 0.0)
    print(f"Plot {filename}  (time={d['time']})")

    import matplotlib

    if not args.show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if d["ux"] is not None:
        fig, ax = plot_streamplot(
            d["x"], d["y"], total_temp, d["ux"], d["uy"],
            title=f"T, t={d['time']:.2f}", return_fig=True,
        )
    else:
        fig, ax = plot_contour(
            d["x"], d["y"], total_temp, title=f"T, t={d['time']:.2f}",
            return_fig=True,
        )
    if d["mask"] is not None:
        xx, yy = np.meshgrid(d["x"], d["y"], indexing="ij")
        ax.contour(xx, yy, d["mask"], levels=[0.5], colors="k", linewidths=1.0)
    fig.savefig(args.out, bbox_inches="tight", dpi=200)
    print(f" ==> {args.out}")

    if args.vorticity and d["vorticity"] is not None:
        fig2, _ = plot_streamplot(
            d["x"], d["y"], d["vorticity"], d["ux"], d["uy"],
            title="vorticity", return_fig=True,
        )
        out2 = args.out.replace(".png", "_vorticity.png")
        fig2.savefig(out2, bbox_inches="tight", dpi=200)
        print(f" ==> {out2}")

    if args.show:
        plt.show()
    return 0


if __name__ == "__main__":
    sys.exit(main())
