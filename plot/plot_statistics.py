"""Plot the running-average statistics file (data/statistics.h5).

Counterpart of the reference's plot/plot_statistics.py: mean temperature with
mean-flow streamlines, and the pointwise Nusselt field.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from plot_utils import plot_streamplot  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--file", default="data/statistics.h5")
    ap.add_argument("--out", default="statistics.png")
    ap.add_argument("--show", action="store_true")
    args = ap.parse_args()

    import h5py

    with h5py.File(args.file, "r") as f:
        t = np.asarray(f["temp/v"])
        u = np.asarray(f["ux/v"])
        v = np.asarray(f["uy/v"])
        n = np.asarray(f["nusselt/v"])
        x = np.asarray(f["temp/x"] if "temp/x" in f else f["x"])
        y = np.asarray(f["temp/y"] if "temp/y" in f else f["y"])

    import matplotlib

    if not args.show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, _ = plot_streamplot(x, y, t, u, v, title="mean T", return_fig=True)
    fig.savefig(args.out, bbox_inches="tight", dpi=200)
    print(f" ==> {args.out}")
    fig2, _ = plot_streamplot(
        x, y, n, u, v, diverging=False, title="pointwise Nu", return_fig=True
    )
    out2 = args.out.replace(".png", "_nusselt.png")
    fig2.savefig(out2, bbox_inches="tight", dpi=200)
    print(f" ==> {out2}")
    if args.show:
        plt.show()
    return 0


if __name__ == "__main__":
    sys.exit(main())
