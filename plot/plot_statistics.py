"""Plot running-average statistics files.

Counterpart of the reference's plot/plot_statistics.py — mean temperature
with mean-flow streamlines, and the pointwise Nusselt field — reading BOTH
layouts:

* the legacy ``data/statistics.h5`` layout (models/statistics.py and the
  stats engine's single-model export: root groups ``temp/ux/uy/nusselt``),
* the stats engine's ensemble export (rustpde_mpi_tpu.export_stats:
  per-member groups ``member{i}/...`` + a root ``members`` scalar) —
  select the member with ``--member`` (default 0).

Engine exports additionally carry ``profiles/`` (mean T, RMS profiles,
convective flux) which ``--profiles`` renders as a third figure.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from plot_utils import plot_streamplot  # noqa: E402


def stats_root(f, member: int):
    """The group holding the ``temp/ux/uy/...`` layout: the file root for
    legacy/single-model files, ``member<i>`` for ensemble engine exports."""
    if "temp" in f:
        return f
    if "members" in f:
        k = int(np.asarray(f["members"]))
        if member >= k:
            raise SystemExit(f"--member {member} out of range (file has {k})")
        return f[f"member{member}"]
    raise SystemExit(
        "unrecognized statistics layout: neither a root 'temp' group "
        "(legacy/single-model) nor a 'members' scalar (ensemble export)"
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--file", default="data/statistics.h5")
    ap.add_argument("--out", default="statistics.png")
    ap.add_argument("--member", type=int, default=0,
                    help="member group of an ensemble engine export")
    ap.add_argument("--profiles", action="store_true",
                    help="also plot the engine's profiles/ group")
    ap.add_argument("--show", action="store_true")
    args = ap.parse_args()

    import h5py

    with h5py.File(args.file, "r") as f:
        g = stats_root(f, args.member)
        t = np.asarray(g["temp/v"])
        u = np.asarray(g["ux/v"])
        v = np.asarray(g["uy/v"])
        n = np.asarray(g["nusselt/v"])
        x = np.asarray(g["temp/x"] if "temp/x" in g else g["x"])
        y = np.asarray(g["temp/y"] if "temp/y" in g else g["y"])
        profiles = None
        if args.profiles and "profiles" in g:
            profiles = {k: np.asarray(d) for k, d in g["profiles"].items()}

    import matplotlib

    if not args.show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, _ = plot_streamplot(x, y, t, u, v, title="mean T", return_fig=True)
    fig.savefig(args.out, bbox_inches="tight", dpi=200)
    print(f" ==> {args.out}")
    fig2, _ = plot_streamplot(
        x, y, n, u, v, diverging=False, title="pointwise Nu", return_fig=True
    )
    out2 = args.out.replace(".png", "_nusselt.png")
    fig2.savefig(out2, bbox_inches="tight", dpi=200)
    print(f" ==> {out2}")
    if profiles:
        fig3, ax = plt.subplots(1, 2, figsize=(9, 4), sharey=True)
        yy = profiles.get("y", y)
        ax[0].plot(profiles["t_mean"], yy, label="<T>")
        ax[0].plot(profiles["t_rms"], yy, label="T rms")
        ax[0].set_xlabel("temperature")
        ax[0].set_ylabel("y")
        ax[0].legend()
        ax[1].plot(profiles["ux_rms"], yy, label="ux rms")
        ax[1].plot(profiles["uy_rms"], yy, label="uy rms")
        ax[1].plot(profiles["flux"], yy, label="<uy T>")
        ax[1].set_xlabel("velocity / flux")
        ax[1].legend()
        fig3.tight_layout()
        out3 = args.out.replace(".png", "_profiles.png")
        fig3.savefig(out3, bbox_inches="tight", dpi=200)
        print(f" ==> {out3}")
    if args.show:
        plt.show()
    return 0


if __name__ == "__main__":
    sys.exit(main())
