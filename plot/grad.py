"""Plot adjoint / finite-difference gradient fields.

Counterpart of the reference's plot/grad.py: temperature component of
data/grad_adjoint.h5 (and data/grad_fd.h5 when present) with streamlines of
the velocity components.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from plot_utils import plot_streamplot  # noqa: E402


def _plot_one(filename: str, out: str) -> None:
    import h5py

    with h5py.File(filename, "r") as f:
        x = np.asarray(f["temp/x"])
        y = np.asarray(f["temp/y"])
        t = np.asarray(f["temp/v"])
        u = np.asarray(f["ux/v"])
        v = np.asarray(f["uy/v"])
    fig, _ = plot_streamplot(x, y, t, u, v, title=filename, return_fig=True)
    fig.savefig(out, bbox_inches="tight", dpi=200)
    print(f" ==> {out}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--adjoint", default="data/grad_adjoint.h5")
    ap.add_argument("--fd", default="data/grad_fd.h5")
    ap.add_argument("--show", action="store_true")
    args = ap.parse_args()

    import matplotlib

    if not args.show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if os.path.exists(args.adjoint):
        _plot_one(args.adjoint, "grad_adjoint.png")
    if os.path.exists(args.fd):
        _plot_one(args.fd, "grad_fd.png")
    if args.show:
        plt.show()
    return 0


if __name__ == "__main__":
    sys.exit(main())
