"""Animate a run's snapshots (temperature field over time) to mp4/gif.

Counterpart of the reference's plot/plot_anim2d.py; optionally overlays
particle trajectories traced by rustpde_mpi_tpu.tools.ParticleSwarm
(the reference's plot_anim2d_particle.py).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from plot_utils import read_snapshot_fields, sorted_snapshots  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/anim.gif")
    ap.add_argument("--duration", type=float, default=10.0, help="seconds")
    ap.add_argument("--particles", help="trajectory file (time x y rows) to overlay")
    args = ap.parse_args()

    files = sorted_snapshots()
    if not files:
        print("no snapshots found")
        return 1

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib import animation

    frames = []
    for f in files:
        d = read_snapshot_fields(f)
        total = d["temp"] + (d["tempbc"] if d["tempbc"] is not None else 0.0)
        frames.append((d["time"], total))
    x, y = d["x"], d["y"]
    xx, yy = np.meshgrid(x, y, indexing="ij")
    amp = max(float(np.nanmax(np.abs(t))) for _, t in frames) or 1.0
    levels = np.linspace(-amp, amp, 21)

    traj = None
    if args.particles:
        rows = np.loadtxt(args.particles, ndmin=2)
        traj_times = np.unique(rows[:, 0])
        traj = {t: rows[rows[:, 0] == t, 1:3] for t in traj_times}

        def traj_at(t):
            """Trajectory block nearest to the frame time (the two time axes
            are accumulated independently, so exact equality never holds)."""
            if len(traj_times) == 0:
                return None
            i = int(np.argmin(np.abs(traj_times - t)))
            dt_typ = np.median(np.diff(traj_times)) if len(traj_times) > 1 else np.inf
            if abs(traj_times[i] - t) <= dt_typ / 2.0 + 1e-9:
                return traj[traj_times[i]]
            return None

    fig, ax = plt.subplots(figsize=(5, 5))
    ax.set_aspect("equal")

    def draw(i):
        ax.clear()
        t, field = frames[i]
        ax.contourf(xx, yy, field, levels=levels, cmap="RdBu_r")
        ax.set_title(f"t = {t:.2f}")
        if traj is not None:
            p = traj_at(t)
            if p is not None:
                ax.plot(p[:, 0], p[:, 1], ".", color="0.1", ms=2)
        return []

    fps = max(1, int(len(frames) / args.duration))
    anim = animation.FuncAnimation(fig, draw, frames=len(frames))
    anim.save(args.out, writer=animation.PillowWriter(fps=fps))
    print(f" ==> {args.out} ({len(frames)} frames, {fps} fps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
