"""Custom diverging colormap for the RBC plots.

The reference ships a tabulated "goldfish" diverging map and two brand
colors (/root/reference/plot/utils/colors.py: gfblue3/gfred3 +
gfcmap.json).  This rebuild constructs an equivalent blue-white-red
diverging map *programmatically* — smooth linear interpolation through the
same two anchor colors with a white midpoint, plus darkened outer stops so
extreme values stay readable — instead of shipping tabulated segment data.

Use: ``from colors import set_gfcmap; set_gfcmap()`` then ``cmap="gfcmap"``
anywhere matplotlib accepts a registered name.  plot_utils uses it as the
default diverging map when available.
"""

from __future__ import annotations

# anchor colors (same named palette as the reference)
gfblue3 = (0 / 255, 137 / 255, 204 / 255)
gfred3 = (196 / 255, 0 / 255, 96 / 255)


def _darken(rgb, f=0.45):
    return tuple(c * f for c in rgb)


def gfcmap():
    """Blue-white-red diverging colormap through the goldfish anchors."""
    from matplotlib.colors import LinearSegmentedColormap

    stops = [
        (0.0, _darken(gfblue3)),
        (0.25, gfblue3),
        (0.5, (1.0, 1.0, 1.0)),
        (0.75, gfred3),
        (1.0, _darken(gfred3)),
    ]
    return LinearSegmentedColormap.from_list("gfcmap", stops, N=512)


def set_gfcmap() -> str:
    """Register the map with matplotlib (idempotent); returns the name."""
    import matplotlib

    try:
        matplotlib.colormaps.register(gfcmap(), name="gfcmap")
    except ValueError:
        pass  # already registered
    return "gfcmap"
