"""Shared matplotlib helpers for the post-processing scripts.

Fresh TPU-framework counterpart of the reference's plot/utils/plot_utils.py
(same role: contour + streamline rendering of snapshot fields on the
(x, y) tensor grid).  Color policy: signed fields (temperature fluctuation,
vorticity, adjoint gradients) use a diverging two-hue map centered on zero
(RdBu_r); magnitudes use a single-hue sequential map (viridis); streamlines
are drawn in neutral ink so color stays reserved for the scalar field.
"""

from __future__ import annotations

import numpy as np


def _symmetric_levels(field: np.ndarray, n: int = 21):
    """Contour levels symmetric about 0 for diverging fields."""
    amp = float(np.nanmax(np.abs(field)))
    if amp == 0.0:
        amp = 1.0
    return np.linspace(-amp, amp, n)


def plot_contour(
    x,
    y,
    field,
    ax=None,
    diverging: bool = True,
    cbar: bool = True,
    title: str | None = None,
    return_fig: bool = False,
):
    """Filled contour of ``field`` on the (x, y) grid (indexing='ij')."""
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots(figsize=(5, 5 * (y[-1] - y[0]) / (x[-1] - x[0] + 1e-300)))
    else:
        fig = ax.figure
    xx, yy = np.meshgrid(x, y, indexing="ij")
    if diverging:
        levels = _symmetric_levels(field)
        try:  # custom goldfish-style diverging map (plot/colors.py) —
            # anchored import so a third-party "colors" package on sys.path
            # cannot shadow it; only a missing module falls back
            import importlib.util
            import os as _os

            _spec = importlib.util.spec_from_file_location(
                "_rustpde_plot_colors",
                _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "colors.py"),
            )
            _mod = importlib.util.module_from_spec(_spec)
            _spec.loader.exec_module(_mod)
            cmap = _mod.set_gfcmap()
        except FileNotFoundError:
            cmap = "RdBu_r"
    else:
        levels = 21
        cmap = "viridis"
    im = ax.contourf(xx, yy, field, levels=levels, cmap=cmap)
    ax.set_aspect("equal")
    ax.set_xlabel("x")
    ax.set_ylabel("y")
    if title:
        ax.set_title(title)
    if cbar:
        fig.colorbar(im, ax=ax, shrink=0.8)
    if return_fig:
        return fig, ax
    return ax


def plot_streamplot(
    x,
    y,
    field,
    u,
    v,
    ax=None,
    diverging: bool = True,
    cbar: bool = True,
    density: float = 1.2,
    title: str | None = None,
    return_fig: bool = False,
):
    """Filled contour of ``field`` with velocity streamlines on top.

    Streamplot requires a uniform grid; the (Chebyshev) fields are resampled
    onto one by linear interpolation, like the reference's helper."""
    fig, ax = plot_contour(
        x, y, field, ax=ax, diverging=diverging, cbar=cbar, title=title,
        return_fig=True,
    )
    if u is not None and v is not None:
        xi = np.linspace(x[0], x[-1], len(x))
        yi = np.linspace(y[0], y[-1], len(y))
        u_i = _resample(x, y, u, xi, yi)
        v_i = _resample(x, y, v, xi, yi)
        # streamplot wants (ny, nx) row-major over meshgrid(xi, yi)
        ax.streamplot(
            xi,
            yi,
            u_i.T,
            v_i.T,
            density=density,
            color="0.25",
            linewidth=0.8,
            arrowsize=0.8,
        )
    if return_fig:
        return fig, ax
    return ax


def _resample(x, y, f, xi, yi):
    """Bilinear resample of f(x, y) onto the (xi, yi) tensor grid."""
    fx = np.empty((xi.size, y.size))
    for j in range(y.size):
        fx[:, j] = np.interp(xi, x, f[:, j])
    out = np.empty((xi.size, yi.size))
    for i in range(xi.size):
        out[i, :] = np.interp(yi, y, fx[i, :])
    return out


def read_snapshot_fields(filename: str):
    """Read the plotting-relevant datasets of one snapshot; missing groups
    come back as None (the reference's plot2d.py try/except ladder)."""
    import h5py

    out = {}
    with h5py.File(filename, "r") as f:
        def get(key):
            return np.asarray(f[key]) if key in f else None

        out["x"] = get("temp/x")
        out["y"] = get("temp/y")
        out["temp"] = get("temp/v")
        out["tempbc"] = get("tempbc/v")
        out["ux"] = get("ux/v")
        out["uy"] = get("uy/v")
        out["pres"] = get("pres/v")
        out["vorticity"] = get("vorticity/v")
        out["mask"] = get("solid/mask")
        out["time"] = float(np.asarray(f["time"])) if "time" in f else None
    return out


def sorted_snapshots(patterns=("*.h5", "data/*.h5")):
    """Flow-snapshot files sorted by their stored time scalar (filename time
    as fallback).  Non-snapshot h5 files in the same directory (e.g.
    data/statistics.h5, cartesian.nc sidecars) are excluded by requiring the
    ``temp/v`` dataset + a ``time`` scalar."""
    import glob
    import os
    import re

    import h5py

    files = []
    for pat in patterns:
        files.extend(glob.glob(pat))
    keyed = []
    for f in sorted(set(files)):
        try:
            with h5py.File(f, "r") as h5:
                if "temp/v" not in h5:
                    continue
                if "time" in h5:
                    t = float(np.asarray(h5["time"]))
                else:
                    m = re.findall(r"\d+\.\d+", os.path.basename(f))
                    t = float(m[0]) if m else 0.0
        except OSError:
            continue
        keyed.append((t, f))
    keyed.sort(key=lambda p: p[0])
    return [f for _, f in keyed]
