"""Repo-level developer tooling (not shipped with the package).

``tools.lint`` is the project-specific static-analysis layer — see
``scripts/lint.py`` for the CLI and README "Static analysis & sanitizer"
for the rule inventory.
"""
