"""Project-specific static analysis for rustpde_mpi_tpu.

Two layers, one CLI (``scripts/lint.py``):

* **Project rules** (``project_rules.py``, ids ``RPD0xx``) — AST rules
  distilled from this repo's own fixed-bug history: every rule encodes a
  bug shape a past PR shipped and a review caught (see README "Static
  analysis & sanitizer" for the rule -> historical bug table).
* **Generic rules** (``generic_rules.py``, ids ``GEN-*``) — the curated
  ruff subset this repo cares about (unused imports/locals, mutable
  default args, f-strings without placeholders), run through ``ruff``
  when it is installed and through a built-in AST fallback otherwise
  (this container has no ruff and nothing may be pip-installed).

Grandfathered findings live in ``tools/lint/baseline.json`` with a written
reason each; new findings exit nonzero.  One-line inline suppression:
``# lint-ok: RPD005 <reason>`` (a reason is mandatory — a bare suppression
is itself a finding).
"""

from .core import (  # noqa: F401
    Finding,
    LintResult,
    collect_files,
    lint_source,
    run_lint,
)
