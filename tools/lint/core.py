"""Lint engine: file collection, suppression, baseline, runner.

Findings are matched against the baseline by ``(rule, path, context,
snippet)`` — deliberately line-number-free, so unrelated edits above a
grandfathered finding don't resurrect it, while any change to the flagged
line itself re-reports it for a fresh look.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "lint", "baseline.json")

#: repo-relative roots scanned by default.  ``__graft_entry__.py`` is the
#: external harness shim and stays out of scope.
DEFAULT_SCOPE = (
    "rustpde_mpi_tpu",
    "scripts",
    "tools",
    "tests",
    "examples",
    "plot",
    "bench.py",
)
_EXCLUDE_PARTS = {"__pycache__", ".jax_cache", "data"}

_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*(\S.*)$")
_RULE_TOKEN_RE = re.compile(r"^(RPD\d+|GEN-[A-Z0-9]+|all)$")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    snippet: str = ""
    context: str = "<module>"

    def key(self) -> tuple:
        return (self.rule, self.path, self.context, self.snippet)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclasses.dataclass
class LintResult:
    new: list  # unsuppressed, un-baselined findings (these fail the run)
    baselined: list
    suppressed: int
    files: int
    engine: str  # "ruff" | "fallback" for the generic layer
    stale_baseline: list  # baseline entries that no longer match anything

    @property
    def counts(self) -> dict:
        out: dict[str, int] = {}
        for f in self.new:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    @property
    def baselined_counts(self) -> dict:
        out: dict[str, int] = {}
        for f in self.baselined:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


class Module:
    """One parsed source file handed to every rule."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node, message: str, context: str = "<module>") -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule,
            path=self.relpath,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=self.snippet(line),
            context=context,
        )


def collect_files(root: str = REPO_ROOT, paths=None) -> list[str]:
    """Repo-relative .py files in scope (sorted, deterministic)."""
    rels: list[str] = []
    scope = paths if paths else DEFAULT_SCOPE
    for entry in scope:
        full = os.path.join(root, entry)
        if os.path.isfile(full) and entry.endswith(".py"):
            rels.append(entry)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_PARTS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rels.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return sorted(set(r.replace(os.sep, "/") for r in rels))


def _suppressions(module: Module) -> tuple[dict[int, set], list[Finding]]:
    """Per-line ``# lint-ok: <RULES> <reason>`` suppressions.  A suppression
    without a reason is itself a finding (RPD000) — grandfathering demands
    a written why, inline or in the baseline."""
    table: dict[int, set] = {}
    bad: list[Finding] = []
    for i, text in enumerate(module.lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        # leading rule-shaped tokens (comma- or space-separated) are the
        # rule list; everything after the first non-rule token is the reason
        tokens = m.group(1).split()
        rules: set = set()
        reason_at = len(tokens)
        for j, tok in enumerate(tokens):
            tok = tok.rstrip(",")
            if _RULE_TOKEN_RE.match(tok):
                rules.add(tok)
            else:
                reason_at = j
                break
        if not rules:
            continue  # prose mentioning the marker, not a suppression attempt
        if reason_at >= len(tokens):
            bad.append(
                Finding(
                    rule="RPD000",
                    path=module.relpath,
                    line=i,
                    col=0,
                    message="lint-ok suppression without a reason",
                    snippet=module.snippet(i),
                )
            )
            continue
        table[i] = rules
    return table, bad


def load_baseline(path: str = DEFAULT_BASELINE) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    return payload.get("entries", [])


def save_baseline(entries: list[dict], path: str = DEFAULT_BASELINE) -> None:
    payload = {
        "comment": (
            "Grandfathered lint findings: every entry carries a written "
            "reason.  Matched by (rule, path, context, snippet) — editing "
            "the flagged line re-reports the finding for a fresh look."
        ),
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")


def lint_source(source: str, relpath: str) -> list[Finding]:
    """Run every rule over one in-memory module (the test-fixture entry
    point: ``relpath`` decides rule scoping).  Inline suppressions apply;
    no baseline."""
    from . import generic_rules, project_rules

    module = Module(relpath, source)
    table, bad = _suppressions(module)
    findings = list(bad)
    for rule_fn in project_rules.RULES + generic_rules.RULES:
        findings.extend(rule_fn(module))
    return [
        f
        for f in _dedupe(findings)
        if not (f.rule in table.get(f.line, ()) or "all" in table.get(f.line, ()))
    ]


def _dedupe(findings):
    """Nested functions are visited from every enclosing scope — keep the
    first (outermost-context) finding per (rule, line, col)."""
    seen, out = set(), []
    for f in findings:
        k = (f.rule, f.path, f.line, f.col)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def run_lint(
    root: str = REPO_ROOT,
    paths=None,
    baseline_path: str = DEFAULT_BASELINE,
) -> LintResult:
    from . import generic_rules, project_rules

    files = collect_files(root, paths)
    findings: list[Finding] = []
    suppressed = 0
    parse_failures: list[Finding] = []
    modules: list[Module] = []
    for rel in files:
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                module = Module(rel, fh.read())
        except SyntaxError as exc:
            parse_failures.append(
                Finding(rule="RPD999", path=rel, line=exc.lineno or 1, col=0,
                        message=f"syntax error: {exc.msg}")
            )
            continue
        modules.append(module)

    engine = generic_rules.engine()
    # ruff-engine findings are folded into the per-module stream so inline
    # suppressions apply identically, and their snippet/context are filled
    # from the parsed module so baseline keys stay ENGINE-STABLE (a
    # baseline written on a ruff machine must match on a ruff-less one)
    ruff_by_file: dict[str, list[Finding]] = {}
    if engine == "ruff":
        for f in generic_rules.run_ruff(root, files):
            ruff_by_file.setdefault(f.path, []).append(f)
    for module in modules:
        table, bad = _suppressions(module)
        raw = list(bad)
        for rule_fn in project_rules.RULES:
            raw.extend(rule_fn(module))
        if engine == "fallback":
            for rule_fn in generic_rules.RULES:
                raw.extend(rule_fn(module))
        else:
            for f in ruff_by_file.get(module.relpath, ()):
                f.snippet = module.snippet(f.line)
                raw.append(f)
        for f in _dedupe(raw):
            if f.rule in table.get(f.line, ()) or "all" in table.get(f.line, ()):
                suppressed += 1
            else:
                findings.append(f)
    findings.extend(parse_failures)

    baseline = load_baseline(baseline_path)
    base_keys = {
        (e["rule"], e["path"], e.get("context", "<module>"), e.get("snippet", "")): e
        for e in baseline
    }
    new, baselined, matched = [], [], set()
    for f in findings:
        if f.key() in base_keys:
            baselined.append(f)
            matched.add(f.key())
        else:
            new.append(f)
    stale = [e for k, e in base_keys.items() if k not in matched]
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(
        new=new,
        baselined=baselined,
        suppressed=suppressed,
        files=len(files),
        engine=engine,
        stale_baseline=stale,
    )
