"""Project-specific AST rules (``RPD0xx``), distilled from this repo's own
fixed-bug history — each rule encodes a shape a past PR shipped and review
caught (README "Static analysis & sanitizer" has the rule -> bug table):

* RPD001 — collective call reachable under a host-local condition that did
  not go through ``root_decides``/``broadcast_obj`` (PR 10: the
  ``_fill_slots`` drain check outside the root plan left one host's
  collectives out of phase).  Both shapes are flagged: a collective inside
  the conditional, and an early-exit (return/continue/break) under the
  conditional with collectives later in the same function.
* RPD002 — a collective on an exception path (``except``/``finally``): the
  peer may be dead, the barrier wedges (PR 10 made exception exits skip
  ``sync_hosts`` deliberately).
* RPD003 — use of a buffer after it was passed to a ``donate_argnums``
  position of a jitted callable (PR 1: ``update_n`` dispatches a fresh
  copy so retained refs stay valid — donation invalidates the argument).
* RPD004 — ``os.replace``/``os.rename`` without a parent-directory fsync in
  a durability-critical module (PR 10 satellite: ``os.replace`` alone
  leaves the dirent in page cache; the request-never-lost guarantee must
  cover power loss).
* RPD005 — ``np.asarray``/``np.array``/``jax.device_get`` on a possibly
  sharded array in a multihost code path (PR 5 review: ``np.asarray(leaf)``
  fetches non-addressable shards on the very platform the code targets).
* RPD006 — raw ``os.environ`` read of a ``RUSTPDE_*`` knob outside
  ``config.py``/``utils/faults.py``: every knob must be registered in
  ``config.env_knobs()`` so the README knob table stays complete.
* RPD007 — cross-module private-attribute reach (PR 8 review: HTTP
  handlers reaching into ``sim._drain`` instead of a public surface).
* RPD008 — a ``span(...)`` whose body dispatches collectives without a
  host-aligned tag: the span NAME must be a string literal and its kwarg
  values must not derive from host-local sources (clocks, env, rank
  checks, randomness).  Instrumentation args that differ per host around
  a collective are the desync-by-instrumentation shape the runtime
  sanitizer can only catch once it has already happened.
* RPD010 — compile construction (``jax.jit``, ``pallas_call``, an AOT
  ``.lower(...)``, ``build_model_for_key``, ``_compile_entry_points``)
  reachable from a per-boundary scheduler method (PR 19: cold-start
  elimination only holds if nothing on the chunk-boundary hot path can
  trigger a trace — a jit construction there is a multi-second stall
  inside the serve loop).  Builds belong in ``_build_runner`` /
  ``_warm_build`` / the warm-pool background thread.
* RPD009 — a collective/dispatch call issued after a lease renewal with
  no fencing check between them (PR 18 review, the gang-scheduling
  shape): ``renew()`` raising ``LeaseLost`` marks the replica FENCED,
  and the very next dispatch from a fenced replica races the
  reclaimer's writes.  Any function that renews a fleet lease
  (``renew``/``renew_member``/``_fleet_heartbeat``) must consult the
  fence verdict (``_fence_check()``, ``lease.guard()`` or a read of
  ``_fenced``) before its next collective.
"""

from __future__ import annotations

import ast
import re

# ---------------------------------------------------------------- scoping

PKG = "rustpde_mpi_tpu/"

#: modules where collective-dispatch ordering across hosts matters
MULTIHOST_MODULES = (
    "rustpde_mpi_tpu/parallel/",
    "rustpde_mpi_tpu/serve/",
    "rustpde_mpi_tpu/utils/resilience.py",
    "rustpde_mpi_tpu/utils/checkpoint.py",
    "rustpde_mpi_tpu/utils/io_pipeline.py",
    "rustpde_mpi_tpu/models/campaign.py",
)

#: modules whose on-disk state carries a durability guarantee
DURABLE_MODULES = (
    "rustpde_mpi_tpu/utils/checkpoint.py",
    "rustpde_mpi_tpu/serve/queue.py",
    "rustpde_mpi_tpu/serve/fleet/",  # leases, heartbeats, continuations
    "rustpde_mpi_tpu/utils/journal.py",
    "rustpde_mpi_tpu/utils/io_pipeline.py",
    "rustpde_mpi_tpu/utils/slice_io.py",
)

#: host-value collectives + the jit dispatch entry points every host must
#: execute in lockstep (vmapped/scanned step dispatches, slot mutations)
COLLECTIVE_CALLS = {
    "sync_hosts",
    "broadcast",
    "broadcast_obj",
    "allgather_host",
    "root_decides",
}
DISPATCH_CALLS = {
    "update_n",
    "update_n_pending",
    "set_member",
    "mark_dead",
    "respawn_dead",
    "set_dt",
    "write_sharded_snapshot",
}

#: going through one of these makes a host flag fleet-agreed (allgather
#: returns the identical stacked array on every host)
SANCTIONED_CALLS = {
    "root_decides",
    "broadcast",
    "broadcast_obj",
    "allgather_host",
    "_drain_agreed",
}

_HOST_LOCAL_ATTR_RE = re.compile(r"(^|_)(drain|preempt|sig(nal|term|int)?)", re.I)
_HOST_LOCAL_CALLS = {
    "process_index",
    "is_root",
    "getenv",
    "exists",
    "isfile",
    "isdir",
    "glob",
    "time",
    "monotonic",
    "perf_counter",
    "random",
    "uniform",
    "randint",
}


def _in(relpath: str, prefixes) -> bool:
    return any(relpath.startswith(p) for p in prefixes)


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _contains_call(expr: ast.AST, names) -> bool:
    return any(
        isinstance(n, ast.Call) and _call_name(n) in names for n in ast.walk(expr)
    )


def _functions(tree):
    """Yield (qualname, FunctionDef) for every function/method."""

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield f"{prefix}{child.name}", child
                yield from walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def _max_lineno(node: ast.AST) -> int:
    return max(
        (getattr(n, "lineno", 0) for n in ast.walk(node)), default=0
    )


# ------------------------------------------------- RPD001 host-local gating


def _is_host_local(expr: ast.AST, tainted: set, cleared: set = frozenset()) -> bool:
    """True when ``expr`` derives from a host-local source and was not
    routed through a sanctioning broadcast.  ``cleared`` holds names that
    were assigned from a sanctioning call — they beat the drain/preempt
    name-pattern heuristic (``drain = root_decides(self._drain)`` is the
    fixed form and must pass clean)."""
    if _contains_call(expr, SANCTIONED_CALLS):
        return False
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and _call_name(n) in _HOST_LOCAL_CALLS:
            return True
        if isinstance(n, ast.Attribute):
            if n.attr == "environ" or _HOST_LOCAL_ATTR_RE.search(n.attr):
                return True
        if isinstance(n, ast.Name) and n.id not in cleared:
            if n.id in tainted or _HOST_LOCAL_ATTR_RE.search(n.id):
                return True
    return False


def rule_collective_under_host_local(module) -> list:
    """RPD001 (the PR-10 drain-check shape)."""
    if not _in(module.relpath, MULTIHOST_MODULES):
        return []
    out = []
    collective = COLLECTIVE_CALLS | DISPATCH_CALLS
    for qualname, fn in _functions(module.tree):
        # linear taint pass: names assigned from host-local sources vs
        # names explicitly routed through a sanctioning broadcast
        tainted: set = set()
        cleared: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                name = node.targets[0].id
                if _contains_call(node.value, SANCTIONED_CALLS):
                    tainted.discard(name)
                    cleared.add(name)
                elif _is_host_local(node.value, tainted, cleared):
                    tainted.add(name)
                    cleared.discard(name)
        # collective call sites in this function, by line
        call_lines = [
            n.lineno
            for n in ast.walk(fn)
            if isinstance(n, ast.Call) and _call_name(n) in collective
        ]
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            if not _is_host_local(node.test, tainted, cleared):
                continue
            branch_nodes = node.body + node.orelse
            # shape (a): collective inside the host-local conditional
            for stmt in branch_nodes:
                for n in ast.walk(stmt):
                    if isinstance(n, ast.Call) and _call_name(n) in collective:
                        out.append(
                            module.finding(
                                "RPD001",
                                n,
                                f"collective/dispatch call '{_call_name(n)}' under a "
                                "host-local condition — route the decision through "
                                "root_decides/broadcast_obj first",
                                qualname,
                            )
                        )
            # shape (b): early-exit under the conditional, collectives later
            has_exit = any(
                isinstance(n, (ast.Return, ast.Continue, ast.Break))
                for stmt in branch_nodes
                for n in ast.walk(stmt)
            )
            if has_exit:
                end = _max_lineno(node)
                if any(line > end for line in call_lines):
                    out.append(
                        module.finding(
                            "RPD001",
                            node,
                            "early-exit under a host-local condition skips the "
                            "collective calls below on THIS host only — hoist the "
                            "decision into the root plan (root_decides/broadcast_obj)",
                            qualname,
                        )
                    )
    return out


# ------------------------------------------------ RPD002 sync on except


def rule_collective_on_exception_path(module) -> list:
    if not module.relpath.startswith(PKG):
        return []
    out = []
    for qualname, fn in _functions(module.tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Try):
                continue
            regions = [(h.body, "except") for h in node.handlers]
            regions.append((node.finalbody, "finally"))
            for body, kind in regions:
                for stmt in body:
                    for n in ast.walk(stmt):
                        if isinstance(n, ast.Call) and _call_name(n) in COLLECTIVE_CALLS:
                            out.append(
                                module.finding(
                                    "RPD002",
                                    n,
                                    f"collective '{_call_name(n)}' on a {kind} path — "
                                    "the peer may be dead; exception exits must skip "
                                    "barriers (journaled structured exit instead)",
                                    qualname,
                                )
                            )
    return out


# ------------------------------------------------ RPD003 use after donate


def _donated_positions(call: ast.Call):
    """``jax.jit(..., donate_argnums=...)`` -> set of donated positions."""
    if _call_name(call) != "jit":
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                vals = set()
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                        vals.add(elt.value)
                return vals
    return None


def _target_key(node):
    if isinstance(node, ast.Name):
        return ("name", node.id)
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return ("self", node.attr)
    return None


def rule_use_after_donate(module) -> list:
    if not module.relpath.startswith(PKG):
        return []
    # pass 1: donated callables bound to locals or self attributes
    donated: dict = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donated_positions(node.value)
            if pos:
                for tgt in node.targets:
                    key = _target_key(tgt)
                    if key:
                        donated[key] = pos
    if not donated:
        return []
    out = []
    for qualname, fn in _functions(module.tree):
        consumed: dict[str, int] = {}  # name -> line it was donated on

        def scan(node):
            if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # own scope: params shadow, nested defs get their own pass
            if isinstance(node, ast.Call):
                # argument loads happen at call evaluation, BEFORE the
                # donation invalidates the buffer — scan children first
                for child in ast.iter_child_nodes(node):
                    scan(child)
                key = _target_key(node.func)
                if key in donated:
                    for i, arg in enumerate(node.args):
                        if i in donated[key] and isinstance(arg, ast.Name):
                            consumed[arg.id] = node.lineno
                return
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load) and node.id in consumed:
                    out.append(
                        module.finding(
                            "RPD003",
                            node,
                            f"'{node.id}' used after being passed to a "
                            f"donate_argnums position (donated at line "
                            f"{consumed[node.id]}) — the buffer is invalidated; "
                            "dispatch a fresh copy or re-bind the result",
                            qualname,
                        )
                    )
                elif isinstance(node.ctx, ast.Store):
                    consumed.pop(node.id, None)
                return
            if isinstance(node, ast.Assign):
                scan(node.value)  # RHS consumes before LHS re-binds
                for tgt in node.targets:
                    scan(tgt)
                return
            for child in ast.iter_child_nodes(node):
                scan(child)

        for stmt in fn.body:
            scan(stmt)
    return out


# --------------------------------------------- RPD004 replace w/o dirsync


def rule_replace_without_dirsync(module) -> list:
    if not _in(module.relpath, DURABLE_MODULES):
        return []
    out = []
    for qualname, fn in _functions(module.tree):
        has_dirsync = any(
            isinstance(n, ast.Call) and "fsync_dir" in _call_name(n)
            for n in ast.walk(fn)
        )
        if has_dirsync:
            continue
        for n in ast.walk(fn):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == "os"
                and n.func.attr in ("replace", "rename")
            ):
                out.append(
                    module.finding(
                        "RPD004",
                        n,
                        f"os.{n.func.attr} in a durability-critical module without a "
                        "parent-directory fsync — the dirent stays in page cache "
                        "across power loss; call utils.fsutil.fsync_dir after the "
                        "rename",
                        qualname,
                    )
                )
    return out


# ----------------------------------------- RPD005 asarray on sharded array


_HOST_SAFE_CALLS = {
    "allgather_host",
    "host_local_array",
    "process_allgather",
    "addressable_data",
}


def _arg_is_host_safe(arg: ast.AST) -> bool:
    if isinstance(arg, (ast.Constant, ast.List, ast.Tuple, ast.Dict)):
        return True
    # h5py/dict subscripts (``h5["time"]``) are host-side reads, and
    # float()/int()/len() casts force a host scalar before asarray sees it
    if isinstance(arg, ast.Subscript):
        return True
    if isinstance(arg, ast.Call):
        name = _call_name(arg)
        if name in _HOST_SAFE_CALLS:
            return True
        if isinstance(arg.func, ast.Name) and arg.func.id in (
            "float",
            "int",
            "bool",
            "len",
            "str",
            "bytes",
        ):
            return True
        # np.*(...) / numpy.*(...) construct host arrays
        f = arg.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) and f.value.id in (
            "np",
            "numpy",
        ):
            return True
    for n in ast.walk(arg):
        if isinstance(n, ast.Attribute) and n.attr in (
            "addressable_shards",
            "addressable_data",
        ):
            return True
    return False


def rule_asarray_on_sharded(module) -> list:
    scope = (
        "rustpde_mpi_tpu/parallel/multihost.py",
        "rustpde_mpi_tpu/utils/checkpoint.py",
        "rustpde_mpi_tpu/utils/resilience.py",
        "rustpde_mpi_tpu/utils/io_pipeline.py",
        "rustpde_mpi_tpu/serve/",  # the serve/ prefix covers serve/fleet/
        "rustpde_mpi_tpu/serve/fleet/",  # explicit: day-one durability scope
        "rustpde_mpi_tpu/models/campaign.py",
    )
    if not _in(module.relpath, scope):
        return []
    out = []
    for qualname, fn in _functions(module.tree):
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            fetch = None
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                if f.value.id in ("np", "numpy") and f.attr in ("asarray", "array"):
                    fetch = f"np.{f.attr}"
                if f.value.id == "jax" and f.attr == "device_get":
                    fetch = "jax.device_get"
            if fetch is None or not n.args:
                continue
            if _arg_is_host_safe(n.args[0]):
                continue
            out.append(
                module.finding(
                    "RPD005",
                    n,
                    f"{fetch} on a possibly-sharded array in a multihost code "
                    "path — fetches non-addressable shards (PR-5 bug shape); use "
                    "addressable_shards/host_local_array or build from dtype "
                    "metadata, or mark the value '# lint-ok: RPD005 <why host-"
                    "local>'",
                    qualname,
                )
            )
    return out


# --------------------------------------------------- RPD006 raw env reads


def rule_raw_env_read(module) -> list:
    if not module.relpath.startswith(PKG):
        return []
    if module.relpath in (
        "rustpde_mpi_tpu/config.py",  # the registry itself
        "rustpde_mpi_tpu/utils/faults.py",  # import-light by design (no jax)
    ):
        return []
    out = []
    for qualname, fn in _functions(module.tree):
        for n in ast.walk(fn):
            key = None
            if isinstance(n, ast.Call):
                name = _call_name(n)
                if name in ("get", "getenv") and n.args:
                    target = n.func
                    is_env = name == "getenv" or (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Attribute)
                        and target.value.attr == "environ"
                    )
                    if is_env and isinstance(n.args[0], ast.Constant):
                        key = n.args[0].value
            elif isinstance(n, ast.Subscript) and isinstance(n.ctx, ast.Load):
                v = n.value
                if isinstance(v, ast.Attribute) and v.attr == "environ":
                    if isinstance(n.slice, ast.Constant):
                        key = n.slice.value
            if isinstance(key, str) and key.startswith("RUSTPDE_"):
                out.append(
                    module.finding(
                        "RPD006",
                        n,
                        f"raw os.environ read of {key!r} outside config.py — go "
                        "through config.env_get so the knob is registered in "
                        "env_knobs() and the README knob table stays complete",
                        qualname,
                    )
                )
    # module-level reads (outside any function)
    return out + _module_level_env_reads(module)


def _module_level_env_reads(module) -> list:
    out = []
    fn_ranges = []
    for _, fn in _functions(module.tree):
        fn_ranges.append((fn.lineno, _max_lineno(fn)))

    def in_fn(line):
        return any(a <= line <= b for a, b in fn_ranges)

    for n in ast.walk(module.tree):
        if in_fn(getattr(n, "lineno", 0)):
            continue
        key = None
        if isinstance(n, ast.Call):
            name = _call_name(n)
            if name in ("get", "getenv") and n.args:
                target = n.func
                is_env = name == "getenv" or (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Attribute)
                    and target.value.attr == "environ"
                )
                if is_env and isinstance(n.args[0], ast.Constant):
                    key = n.args[0].value
        elif isinstance(n, ast.Subscript) and isinstance(n.ctx, ast.Load):
            v = n.value
            if isinstance(v, ast.Attribute) and v.attr == "environ":
                if isinstance(n.slice, ast.Constant):
                    key = n.slice.value
        if isinstance(key, str) and key.startswith("RUSTPDE_"):
            out.append(
                module.finding(
                    "RPD006",
                    n,
                    f"raw os.environ read of {key!r} at module "
                    "level — go through config.env_get",
                )
            )
    return out


# -------------------------------------- RPD008 span tag around collectives


def rule_span_collective_tag(module) -> list:
    """RPD008: ``with span(...)`` bodies that dispatch collectives must
    carry a host-aligned tag — literal name, no host-local kwarg values.

    A span is pure host-side bookkeeping, BUT its argument expressions are
    evaluated on every host: a name or kwarg computed from a clock, the
    rank, the environment or randomness documents a DIFFERENT story per
    host around the very dispatch that must stay in lockstep — and when
    the recorded tags disagree, the flight recorders of a desynced fleet
    cannot even be lined up to diagnose it.  The sanitizer catches the
    desync at runtime; this catches the shape at review time."""
    if not _in(module.relpath, MULTIHOST_MODULES):
        return []
    out = []
    collective = COLLECTIVE_CALLS | DISPATCH_CALLS
    for qualname, fn in _functions(module.tree):
        # reuse RPD001's linear taint pass so sanctioned root-plan values
        # (n = broadcast_obj(...)) stay clean span args
        tainted: set = set()
        cleared: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                name = node.targets[0].id
                if _contains_call(node.value, SANCTIONED_CALLS):
                    tainted.discard(name)
                    cleared.add(name)
                elif _is_host_local(node.value, tainted, cleared):
                    tainted.add(name)
                    cleared.discard(name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            spans = [
                item.context_expr
                for item in node.items
                if isinstance(item.context_expr, ast.Call)
                and _call_name(item.context_expr) == "span"
            ]
            if not spans:
                continue
            dispatches = any(
                isinstance(n, ast.Call) and _call_name(n) in collective
                for stmt in node.body
                for n in ast.walk(stmt)
            )
            if not dispatches:
                continue
            for call in spans:
                name_ok = (
                    call.args
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                )
                if not name_ok:
                    out.append(
                        module.finding(
                            "RPD008",
                            call,
                            "span wrapping a collective dispatch needs a "
                            "LITERAL name — a computed tag can differ per "
                            "host around the very call that must stay in "
                            "lockstep",
                            qualname,
                        )
                    )
                for kw in call.keywords:
                    if _is_host_local(kw.value, tainted, cleared):
                        out.append(
                            module.finding(
                                "RPD008",
                                kw.value,
                                f"span kwarg '{kw.arg}' around a collective "
                                "dispatch derives from a host-local source "
                                "(clock/env/rank/random) — record a root-"
                                "broadcast value or move the measurement "
                                "outside the span",
                                qualname,
                            )
                        )
    return out


# ---------------------------------- RPD009 dispatch after renew, no fence

RENEW_CALLS = {"renew", "renew_member", "_fleet_heartbeat"}
FENCE_CHECK_CALLS = {"_fence_check", "guard"}


def rule_dispatch_after_renew_without_fence(module) -> list:
    """RPD009: inside a lease-fenced scheduler region — a function that
    renews a fleet lease — every collective/dispatch call lexically after
    the renewal must have a fence consult between the renew and itself.

    A renew that raises ``LeaseLost`` means a survivor broke this
    replica's lease and already owns the bucket: the replica is FENCED,
    and any dispatch it still issues (a barrier the reclaimer never
    joins, a slot mutation racing the reclaimer's own) is the
    split-brain shape the fencing tokens exist to kill.  The fence
    consult is a call to ``_fence_check``/``guard`` or a read of the
    ``_fenced`` flag."""
    if not _in(module.relpath, MULTIHOST_MODULES):
        return []
    out = []
    collective = COLLECTIVE_CALLS | DISPATCH_CALLS
    for qualname, fn in _functions(module.tree):
        renew_lines: list[int] = []
        fence_lines: list[int] = []
        dispatches: list[ast.Call] = []
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                name = _call_name(n)
                if name in RENEW_CALLS:
                    renew_lines.append(n.lineno)
                elif name in FENCE_CHECK_CALLS:
                    fence_lines.append(n.lineno)
                elif name in collective:
                    dispatches.append(n)
            elif (
                isinstance(n, ast.Attribute)
                and n.attr == "_fenced"
                and isinstance(n.ctx, ast.Load)
            ):
                fence_lines.append(n.lineno)
        if not renew_lines or not dispatches:
            continue
        first_renew = min(renew_lines)
        for n in dispatches:
            if n.lineno <= first_renew:
                continue
            if any(first_renew <= f <= n.lineno for f in fence_lines):
                continue
            out.append(
                module.finding(
                    "RPD009",
                    n,
                    f"collective/dispatch '{_call_name(n)}' after a lease "
                    "renewal with no fencing check between them — a renew "
                    "that raised LeaseLost leaves this replica FENCED and "
                    "its next dispatch races the reclaimer; consult "
                    "_fence_check()/guard()/_fenced first",
                    qualname,
                )
            )
    return out


# ---------------------------------- RPD010 compile construction per boundary

#: scheduler methods that run at EVERY chunk boundary of a live campaign —
#: the latency-critical region cold-start work must never leak into
PER_BOUNDARY_METHODS = {
    "_campaign_loop",
    "_settle_boundary",
    "_fill_slots",
    "_settle_predivergence",
    "_maybe_preempt",
    "_handle_death",
    "_flush_results",
    "_refresh_slot_state",
    "_fence_check",
    "_boundary_gauges",
}

#: calls that construct (or force) an XLA compile when they execute
COMPILE_CONSTRUCTION_CALLS = {
    "jit",
    "pallas_call",
    "build_model_for_key",
    "aot_compile",
    "compile_entry_points",
    "_compile_entry_points",
    "_compile_entry_points_impl",
}


def rule_compile_in_boundary_path(module) -> list:
    """RPD010: no compile construction on the per-boundary hot path.

    The warm pool / AOT machinery (PR 19) moves every trace+compile to
    campaign OPEN (``_build_runner``) or the background warm-pool builder
    (``_warm_build``); a ``jax.jit``/``pallas_call``/``.lower()`` call
    that executes inside a per-boundary method re-introduces the
    multi-second stall in the middle of a live campaign, where it also
    skews the boundary budget the governor steers by.  ``.lower`` is only
    flagged when called WITH arguments (a jit AOT lowering takes the
    concrete args; an argument-less ``.lower()`` is ``str.lower``)."""
    if not module.relpath.startswith("rustpde_mpi_tpu/serve/"):
        return []
    out = []
    for qualname, fn in _functions(module.tree):
        if fn.name not in PER_BOUNDARY_METHODS:
            continue
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            if name not in COMPILE_CONSTRUCTION_CALLS and not (
                name == "lower" and (n.args or n.keywords)
            ):
                continue
            out.append(
                module.finding(
                    "RPD010",
                    n,
                    f"compile construction '{name}' inside per-boundary "
                    f"method '{fn.name}' — a trace/compile here stalls a "
                    "LIVE campaign for seconds at a chunk boundary; move "
                    "the build to _build_runner/_warm_build (campaign "
                    "open or the warm-pool background thread)",
                    qualname,
                )
            )
    return out


# ------------------------------------------- RPD007 cross-module privates


_NAMEDTUPLE_OK = {"_fields", "_replace", "_asdict", "_make", "_field_defaults"}


def rule_cross_module_private(module) -> list:
    if not module.relpath.startswith(PKG):
        return []
    imported_modules: set = set()
    imported_symbols: set = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                # only the package's own modules: stdlib privates
                # (sys._getframe, os._exit) are established idioms
                if alias.name.startswith("rustpde"):
                    imported_modules.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module != "__future__":
            if node.level == 0 and not (node.module or "").startswith("rustpde"):
                continue
            for alias in node.names:
                imported_symbols.add(alias.asname or alias.name)
    out = []
    for qualname, fn in _functions(module.tree):
        # locals constructed from imported classes: v = ImportedThing(...)
        constructed: set = set()
        for n in ast.walk(fn):
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)
                and isinstance(n.value.func, ast.Name)
                and n.value.func.id in imported_symbols
                and n.value.func.id[:1].isupper()
            ):
                constructed.add(n.targets[0].id)
        for n in ast.walk(fn):
            if not isinstance(n, ast.Attribute):
                continue
            attr = n.attr
            if (
                not attr.startswith("_")
                or attr.startswith("__")
                or attr in _NAMEDTUPLE_OK
            ):
                continue
            base = n.value
            if not isinstance(base, ast.Name) or base.id in ("self", "cls"):
                continue
            if (
                base.id in imported_modules
                or base.id in imported_symbols
                or base.id in constructed
            ):
                out.append(
                    module.finding(
                        "RPD007",
                        n,
                        f"cross-module reach into private '{base.id}.{attr}' — "
                        "promote a public accessor on the owning module instead",
                        qualname,
                    )
                )
    return out


RULES = (
    rule_collective_under_host_local,
    rule_collective_on_exception_path,
    rule_use_after_donate,
    rule_replace_without_dirsync,
    rule_asarray_on_sharded,
    rule_raw_env_read,
    rule_cross_module_private,
    rule_span_collective_tag,
    rule_dispatch_after_renew_without_fence,
    rule_compile_in_boundary_path,
)
