"""Curated generic lint layer: the ruff subset this repo cares about.

When a ``ruff`` binary is on PATH the real tool runs with exactly these
rules (F401 unused import, F841 unused local, B006 mutable default
argument, F541 f-string without placeholders).  This container bakes no
ruff and nothing may be pip-installed, so a built-in AST fallback
implements the same four checks under the same ids — both engines emit
``GEN-Fxxx``/``GEN-B006`` findings so the baseline and the LINT.json
rule->count payload are engine-stable.

The fallback honors ``# noqa`` comments on the flagged line (the repo's
re-export surfaces are annotated ``# noqa: F401`` already) and skips
``__init__.py`` files for F401 (re-exports ARE the point there).
"""

from __future__ import annotations

import ast
import json
import os
import re
import shutil
import subprocess

RUFF_SELECT = "F401,F841,B006,F541"
_RULE_IDS = {"F401": "GEN-F401", "F841": "GEN-F841", "B006": "GEN-B006", "F541": "GEN-F541"}


def engine() -> str:
    return "ruff" if shutil.which("ruff") else "fallback"


def run_ruff(root: str, files: list[str]) -> list:
    """Real-ruff path: curated select list, JSON output mapped to Findings."""
    from .core import Finding

    proc = subprocess.run(
        ["ruff", "check", "--select", RUFF_SELECT, "--output-format", "json", *files],
        cwd=root,
        capture_output=True,
        text=True,
    )
    out = []
    try:
        rows = json.loads(proc.stdout or "[]")
    except json.JSONDecodeError:
        rows = []
    for row in rows:
        rel = os.path.relpath(row["filename"], root).replace(os.sep, "/")
        if rel.endswith("__init__.py") and row["code"] == "F401":
            continue
        out.append(
            Finding(
                rule=_RULE_IDS.get(row["code"], f"GEN-{row['code']}"),
                path=rel,
                line=row["location"]["row"],
                col=row["location"]["column"],
                message=row["message"],
                snippet="",
            )
        )
    return out


def _has_noqa(module, line: int) -> bool:
    text = module.lines[line - 1] if 1 <= line <= len(module.lines) else ""
    return "noqa" in text


# ------------------------------------------------------- GEN-F401


def rule_unused_import(module) -> list:
    if module.relpath.endswith("__init__.py"):
        return []
    imported: dict[str, ast.AST] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                imported[alias.asname or alias.name.split(".")[0]] = node
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                imported[alias.asname or alias.name] = node
    if not imported:
        return []
    used: set = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # root Name covered above
    # names referenced from string constants (quoted annotations, __all__)
    blob = "\n".join(
        n.value for n in ast.walk(module.tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    )
    out = []
    for name, node in imported.items():
        if name in used:
            continue
        if re.search(rf"\b{re.escape(name)}\b", blob):
            continue
        if _has_noqa(module, node.lineno):
            continue
        out.append(
            module.finding("GEN-F401", node, f"unused import '{name}'")
        )
    return out


# ------------------------------------------------------- GEN-F841


def _scope_nodes(fn):
    """The function's OWN-scope nodes: nested classes/functions/lambdas are
    separate scopes (class attributes are not locals; nested defs get their
    own pass).  Loads still count from the whole subtree — closures read
    outer locals."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def rule_unused_local(module) -> list:
    from .project_rules import _functions

    out = []
    for qualname, fn in _functions(module.tree):
        if any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Name)
            and n.func.id in ("locals", "vars", "eval", "exec")
            for n in ast.walk(fn)
        ):
            continue
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)
        assigned: dict[str, ast.AST] = {}
        declared: set = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared.update(node.names)
        for node in _scope_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                name = node.targets[0].id
                if not name.startswith("_") and name not in params and name not in declared:
                    assigned.setdefault(name, node)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                if not node.name.startswith("_"):
                    assigned.setdefault(node.name, node)
            elif isinstance(node, ast.withitem) and isinstance(
                node.optional_vars, ast.Name
            ):
                name = node.optional_vars.id
                if not name.startswith("_") and name not in params:
                    assigned.setdefault(name, node.optional_vars)
        if not assigned:
            continue
        loaded: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                loaded.add(node.id)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Del):
                loaded.add(node.id)
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                loaded.update(node.names)
        # except-handler names are also "loaded" via re-raise idioms the AST
        # shows as Name loads; nothing special needed
        for name, node in assigned.items():
            if name in loaded:
                continue
            line = getattr(node, "lineno", fn.lineno)
            if _has_noqa(module, line):
                continue
            # context stays "<module>" (not the qualname) so the baseline
            # key is identical whichever engine produced the finding
            out.append(
                module.finding(
                    "GEN-F841",
                    node,
                    f"local '{name}' assigned but never used (in {qualname})",
                )
            )
    return out


# ------------------------------------------------------- GEN-B006


def rule_mutable_default(module) -> list:
    from .project_rules import _functions

    out = []
    for qualname, fn in _functions(module.tree):
        for default in list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None
        ]:
            mutable = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            )
            if mutable and not _has_noqa(module, default.lineno):
                out.append(
                    module.finding(
                        "GEN-B006",
                        default,
                        "mutable default argument — shared across calls; use "
                        f"None + in-body construction (in {qualname})",
                    )
                )
    return out


# ------------------------------------------------------- GEN-F541


def rule_fstring_no_placeholder(module) -> list:
    out = []
    # a FormattedValue's format_spec (":.3e") parses as a nested JoinedStr
    # of constants — those are not f-strings in the source, skip them
    spec_ids = {
        id(n.format_spec)
        for n in ast.walk(module.tree)
        if isinstance(n, ast.FormattedValue) and n.format_spec is not None
    }
    for node in ast.walk(module.tree):
        if id(node) in spec_ids:
            continue
        if isinstance(node, ast.JoinedStr) and not any(
            isinstance(v, ast.FormattedValue) for v in node.values
        ):
            if not _has_noqa(module, node.lineno):
                out.append(
                    module.finding(
                        "GEN-F541", node, "f-string without any placeholders"
                    )
                )
    return out


RULES = (
    rule_unused_import,
    rule_unused_local,
    rule_mutable_default,
    rule_fstring_no_placeholder,
)
