// Passive Lagrangian particle tracer — native core.
//
// TPU-framework rebuild of the reference's particle_tracer crate
// (/root/reference/tools/particle_tracer/src/lib.rs): RK4 advection of a
// particle swarm through 2-D velocity snapshots with bilinear interpolation
// on a (possibly non-uniform, e.g. Chebyshev) tensor grid.  The runtime is
// host-side tooling, so it is native C++ like the reference's Rust crate;
// rustpde_mpi_tpu/tools/particle_tracer.py binds it via ctypes (with a numpy
// fallback when the shared library has not been built).
//
// Build:  make            (g++ -O3 -shared -fPIC tracer.cpp -o libtracer.so)
//
// Conventions: fields are row-major (nx, ny); grids ascending; a particle
// whose trajectory leaves the domain freezes in place for the remainder of
// the call (the reference ignores the out-of-bounds error per step,
// lib.rs ParticleSwarm::update).

#include <algorithm>
#include <cstdint>

namespace {

// index of the grid interval containing p: returns i with g[i] <= p < g[i+1],
// clamped to [0, n-2]
inline long interval(const double* g, long n, double p) {
    const double* it = std::upper_bound(g, g + n, p);
    long hi = static_cast<long>(it - g);
    if (hi <= 0) hi = 1;
    if (hi >= n) hi = n - 1;
    return hi - 1;
}

struct Grid {
    const double* x;
    long nx;
    const double* y;
    long ny;
    const double* ux;  // (nx, ny) row-major
    const double* uy;

    bool inside(double px, double py) const {
        return px >= x[0] && px <= x[nx - 1] && py >= y[0] && py <= y[ny - 1];
    }

    // bilinear sample of (ux, uy) at (px, py)
    void sample(double px, double py, double* out) const {
        long i = interval(x, nx, px);
        long j = interval(y, ny, py);
        double dx = x[i + 1] - x[i];
        double dy = y[j + 1] - y[j];
        double tx = (px - x[i]) / dx;
        double ty = (py - y[j]) / dy;
        double w00 = (1.0 - tx) * (1.0 - ty);
        double w01 = (1.0 - tx) * ty;
        double w10 = tx * (1.0 - ty);
        double w11 = tx * ty;
        long base = i * ny + j;
        out[0] = w00 * ux[base] + w01 * ux[base + 1] + w10 * ux[base + ny] +
                 w11 * ux[base + ny + 1];
        out[1] = w00 * uy[base] + w01 * uy[base + 1] + w10 * uy[base + ny] +
                 w11 * uy[base + ny + 1];
    }
};

}  // namespace

extern "C" {

// Advance all particles n_steps RK4 steps of size dt through the (static)
// velocity field.  Positions are updated in place; out-of-bounds particles
// freeze.  Returns the number of particles frozen at exit.
long advect_particles(const double* x, long nx, const double* y, long ny,
                      const double* ux, const double* uy, double* px,
                      double* py, long n_particles, double dt, long n_steps) {
    Grid grid{x, nx, y, ny, ux, uy};
    long frozen = 0;
    for (long p = 0; p < n_particles; ++p) {
        double cx = px[p], cy = py[p];
        bool alive = grid.inside(cx, cy);
        for (long s = 0; s < n_steps && alive; ++s) {
            double k1[2], k2[2], k3[2], k4[2];
            grid.sample(cx, cy, k1);
            double mx = cx + 0.5 * dt * k1[0], my = cy + 0.5 * dt * k1[1];
            if (!grid.inside(mx, my)) { alive = false; break; }
            grid.sample(mx, my, k2);
            mx = cx + 0.5 * dt * k2[0];
            my = cy + 0.5 * dt * k2[1];
            if (!grid.inside(mx, my)) { alive = false; break; }
            grid.sample(mx, my, k3);
            mx = cx + dt * k3[0];
            my = cy + dt * k3[1];
            if (!grid.inside(mx, my)) { alive = false; break; }
            grid.sample(mx, my, k4);
            cx += dt / 6.0 * (k1[0] + 2.0 * k2[0] + 2.0 * k3[0] + k4[0]);
            cy += dt / 6.0 * (k1[1] + 2.0 * k2[1] + 2.0 * k3[1] + k4[1]);
            if (!grid.inside(cx, cy)) { alive = false; break; }
        }
        px[p] = cx;
        py[p] = cy;
        if (!alive) ++frozen;
    }
    return frozen;
}

// Single bilinear sample (exposed for tests / probing snapshots from Python).
void sample_velocity(const double* x, long nx, const double* y, long ny,
                     const double* ux, const double* uy, const double* px,
                     const double* py, long n, double* out_ux,
                     double* out_uy) {
    Grid grid{x, nx, y, ny, ux, uy};
    for (long p = 0; p < n; ++p) {
        double u[2] = {0.0, 0.0};
        if (grid.inside(px[p], py[p])) grid.sample(px[p], py[p], u);
        out_ux[p] = u[0];
        out_uy[p] = u[1];
    }
}

}  // extern "C"
