"""Trace a particle swarm through a run's snapshots (CLI).

Counterpart of the reference's particle_tracer main.rs driver: seed a
rectangle of particles, replay the sorted data/*.h5 snapshots, write the
trajectory as ``time x y`` rows for plot/plot_anim2d.py --particles.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rustpde_mpi_tpu.tools import ParticleSwarm, sorted_h5_files


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="data/")
    ap.add_argument("--x0", type=float, default=0.7)
    ap.add_argument("--y0", type=float, default=-0.7)
    ap.add_argument("--range", type=float, default=0.25)
    ap.add_argument("--n", type=int, default=5000)
    ap.add_argument("--timestep", type=float, default=0.001)
    ap.add_argument("--snapshot-dt", type=float, default=None,
                    help="time between snapshots (default: inferred)")
    ap.add_argument("--out", default="data/trajectories.txt")
    args = ap.parse_args()

    files = [p for _, p in sorted_h5_files(args.root)]
    if len(files) < 2:
        print(f"need >=2 snapshots under {args.root}")
        return 1
    import h5py

    with h5py.File(files[0], "r") as f:
        x = np.asarray(f["ux/x"] if "ux/x" in f else f["x"])
        y = np.asarray(f["ux/y"] if "ux/y" in f else f["y"])
    if args.snapshot_dt is None:
        times = [t for t, _ in sorted_h5_files(args.root)]
        args.snapshot_dt = times[1] - times[0]

    swarm = ParticleSwarm.from_rectangle(
        args.x0, args.y0, args.range, args.n, x, y, args.timestep
    )
    print(f"tracing {args.n} particles through {len(files)} snapshots "
          f"(backend: {swarm.backend})")
    swarm.trace_files(files, args.snapshot_dt)
    swarm.write_history(args.out)
    print(f" ==> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
